"""Address parsing + logger↔dashboard wiring tests."""

import threading
import time

import pytest

from p2pfl_tpu.communication.address import parse_address


def test_parse_ipv4_with_port():
    a = parse_address("10.0.0.1:9000")
    assert a.kind == "ipv4" and a.host == "10.0.0.1" and a.port == 9000
    assert a.target == "10.0.0.1:9000"


def test_parse_assigns_free_port():
    a = parse_address("127.0.0.1")
    assert a.port and a.port > 0
    b = parse_address(None)
    assert b.host == "127.0.0.1" and b.port


def test_parse_ipv6():
    a = parse_address("[::1]:8000")
    assert a.kind == "ipv6" and a.host == "::1" and a.port == 8000


def test_parse_unix_socket():
    a = parse_address("unix:/tmp/x.sock")
    assert a.kind == "unix" and a.target == "unix:/tmp/x.sock"


def test_parse_invalid():
    with pytest.raises(ValueError):
        parse_address("[broken")


def test_logger_web_wiring():
    """register_node + log_metric mirror to the dashboard; monitor runs."""
    import http.server
    import json

    from p2pfl_tpu.management.logger import logger
    from p2pfl_tpu.management.web_services import WebServices
    from p2pfl_tpu.settings import Settings

    Settings.RESOURCE_MONITOR_PERIOD = 0.05
    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, json.loads(body)))
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        logger.connect_web_services(WebServices(f"http://127.0.0.1:{srv.server_port}", "k"))
        logger.register_node("web-node-1", simulation=True)
        logger.log_metric("web-node-1", "acc", 0.9, round=1, experiment="e1")
        logger.log_metric("web-node-1", "loss", 1.0, step=3, round=1, experiment="e1")
        logger.info("web-node-1", "hello dashboard")
        time.sleep(0.3)  # let the monitor tick + the async log queue drain
        logger.unregister_node("web-node-1")
        paths = [p for p, _ in received]
        assert "/node" in paths
        assert "/node-metric/global" in paths
        assert "/node-metric/local" in paths
        assert "/node-metric/system" in paths  # monitor samples
        assert "/node-stop" in paths
        # every log line ships to the dashboard (reference logger.py:224-232),
        # asynchronously via the queue listener
        logs = [b for p, b in received if p == "/node-log"]
        assert any(
            b["address"] == "web-node-1" and "hello dashboard" in b["message"] for b in logs
        )
    finally:
        logger.disconnect_web_services()
        srv.shutdown()


def test_cli_stubs():
    from p2pfl_tpu.cli import main

    assert main(["login"]) == 0
    assert main(["remote"]) == 0
