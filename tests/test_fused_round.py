"""Fused overlay round (ISSUE 6): one donated dispatch per node per round.

Pins the four contracts of the Train→Aggregate seam refactor:

- BIT PARITY: the fused program (``parallel/spmd.py fused_node_round``,
  driven by ``JaxLearner.fused_round``) matches the staged
  ``evaluate()`` + per-epoch ``fit()`` path — params, opt state, the fp32
  partial accumulator and the batch-rng stream — on a fixed seed. The
  staged path stays reachable behind ``Settings.ROUND_FUSED=False``.
- DISPATCH BUDGET: the fused round issues ≤ 2 model-plane device
  dispatches per node per round (fused program + one aggregate) where the
  staged path issues ≥ 1 + epochs + 1.
- DEVICE SEAM: the own contribution carries ``partial_acc`` and FedAvg's
  fold from it matches the restack path.
- FAILURE HYGIENE: a failed fused dispatch restores the rng stream,
  rebuilds the donated opt state and degrades to the staged path;
  ``SpmdFederation`` likewise restores rng on a failed profile and
  rebuilds donated state instead of leaving deleted arrays in the store.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.management.profiling import (
    reset_dispatch_counts,
    snapshot_and_reset_dispatch_counts,
)
from p2pfl_tpu.models import mlp
from p2pfl_tpu.settings import Settings, wire_compression_device


def _max_diff(a, b) -> float:
    return max(
        float(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32)).max())
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _learner(seed_data, addr: str, epochs: int = 2) -> JaxLearner:
    return JaxLearner(
        mlp(seed=0), seed_data, addr=addr, batch_size=64, epochs=epochs, seed=11
    )


@pytest.fixture()
def data():
    return FederatedDataset.synthetic_mnist(n_train=512, n_test=128)


class TestFusedParity:
    def test_fused_matches_staged_bitwise(self, data):
        """Same seed → identical params, opt state, accumulator and rng."""
        staged = _learner(data, "staged")
        fused = _learner(data, "fused")

        staged_metrics = staged.evaluate()
        staged.fit()

        own = fused.fused_round()
        assert own is not None
        assert own.partial_acc is not None

        assert _max_diff(staged.params, fused.params) <= 1e-6
        assert _max_diff(staged.opt_state, fused.opt_state) <= 1e-6
        # partial accumulator == weight × trained params in fp32
        psum, wsum = own.partial_acc
        expect = jax.tree.map(
            lambda p: p.astype(jnp.float32) * float(data.num_samples), staged.params
        )
        assert _max_diff(expect, psum) <= 1e-4
        assert float(wsum) == float(data.num_samples)
        # both paths drew the same batch stream
        assert (
            staged._rng.bit_generator.state == fused._rng.bit_generator.state
        )
        # metrics parity: the stash holds what the staged path floated,
        # including the per-epoch train_loss series at fit()'s step numbers
        stash = fused.pop_round_metrics()
        assert float(stash["test_loss"]) == pytest.approx(
            staged_metrics["test_loss"], abs=1e-6
        )
        assert float(stash["test_acc"]) == pytest.approx(
            staged_metrics["test_acc"], abs=1e-6
        )
        losses, steps = stash["train_loss_series"]
        assert len(np.asarray(losses)) == fused.epochs == len(steps)
        assert steps[-1] == fused._steps_done

    def test_fold_respects_agg_dtype(self, data, monkeypatch):
        """A non-default AGG_DTYPE reaches the fused fold, not just the
        staged fedavg kernel — the accumulator is built in that dtype."""
        monkeypatch.setattr(Settings, "AGG_DTYPE", "float64")
        jax.config.update("jax_enable_x64", True)
        try:
            learner = _learner(data, "dtyped")
            own = learner.fused_round()
            assert own is not None and own.partial_acc is not None
            psum, wsum = own.partial_acc
            assert all(
                leaf.dtype == jnp.float64 for leaf in jax.tree.leaves(psum)
            )
            assert wsum.dtype == jnp.float64
        finally:
            jax.config.update("jax_enable_x64", False)

    def test_interrupt_during_batch_draw_aborts(self, data):
        """interrupt_fit() landing before the dispatch aborts the fused
        round side-effect-free (rng rewound, params untouched)."""
        learner = _learner(data, "interrupted")
        rng_before = learner._rng.bit_generator.state
        params_before = learner.params

        orig = learner.data.epoch_batches

        def draw_then_interrupt(*a, **k):
            learner.interrupt_fit()
            return orig(*a, **k)

        learner.data.epoch_batches = draw_then_interrupt
        try:
            assert learner.fused_round() is None
        finally:
            learner.data.epoch_batches = orig
        assert learner._rng.bit_generator.state == rng_before
        assert learner.params is params_before

    def test_fedavg_fold_matches_restack(self, data):
        """FedAvg from the device accumulator == FedAvg from restacked params."""
        from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
        from p2pfl_tpu.learning.weights import ModelUpdate

        own_learner = _learner(data, "own")
        own = own_learner.fused_round()
        assert own is not None and own.partial_acc is not None
        peer_params = jax.tree.map(lambda p: p + 0.25, own_learner.params)
        peer = ModelUpdate(peer_params, ["peer"], 300)

        agg = FedAvg("own")
        folded = agg.aggregate([own, peer])

        plain_own = ModelUpdate(own.params, own.contributors, own.num_samples)
        restacked = agg.aggregate([plain_own, peer])
        assert _max_diff(folded.params, restacked.params) <= 1e-5
        assert folded.num_samples == restacked.num_samples
        assert folded.contributors == restacked.contributors

    def test_staged_path_reachable_behind_flag(self, data, monkeypatch):
        """ROUND_FUSED=False routes TrainStage through evaluate()+fit()."""
        calls = []
        learner = _learner(data, "flagged")
        monkeypatch.setattr(Settings, "ROUND_FUSED", False)

        orig = JaxLearner.fused_round
        monkeypatch.setattr(
            JaxLearner, "fused_round", lambda self: calls.append("x") or orig(self)
        )
        # the stage-level gate: with the flag off the learner entry point
        # must not even be consulted
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.utils import wait_to_finish

        nodes = []
        full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
        for i in range(2):
            n = Node(learner=_learner(full.partition(i, 2), f"n{i}", epochs=1))
            n.start()
            nodes.append(n)
        try:
            nodes[0].connect(nodes[1].addr)
            time.sleep(0.5)
            nodes[0].set_start_learning(rounds=1, epochs=1)
            wait_to_finish(nodes, timeout=60)
        finally:
            for n in nodes:
                n.stop()
        assert calls == []
        assert _max_diff(
            nodes[0].learner.get_parameters(), nodes[1].learner.get_parameters()
        ) <= 1e-6


class TestDispatchBudget:
    def test_fused_round_two_dispatches_vs_staged(self, data):
        """Fused: ≤ 2 model-plane dispatches/round. Staged: ≥ epochs + 2."""
        from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
        from p2pfl_tpu.learning.weights import ModelUpdate

        # 5 local epochs (the flagship bench's dispatch-split config): the
        # staged path pays 1 eval + 5 train + 1 aggregate dispatches, the
        # fused path 1 program + 1 aggregate — the ≥ 3× CI guard
        epochs = 5

        def one_round(learner, agg, fused: bool):
            agg.set_nodes_to_aggregate([learner.addr, "peer"])
            own = learner.fused_round() if fused else None
            if own is None:
                learner.evaluate()
                learner.fit()
                own = learner.get_model_update()
            agg.add_model(own)
            peer = ModelUpdate(
                jax.tree.map(lambda p: p + 0.1, learner.params), ["peer"], 100
            )
            agg.add_model(peer)
            return agg.wait_and_get_aggregation(timeout=1)

        staged = _learner(data, "staged-n", epochs=epochs)
        reset_dispatch_counts()
        one_round(staged, FedAvg("staged-n"), fused=False)
        # atomic harvest (telemetry registry): read-and-clear in one lock
        # hold, so the next mode's window cannot swallow late increments
        staged_counts = snapshot_and_reset_dispatch_counts()
        staged_total = sum(staged_counts.values())
        assert staged_total >= epochs + 2, staged_counts

        fused = _learner(data, "fused-n", epochs=epochs)
        one_round(fused, FedAvg("fused-n"), fused=True)
        fused_counts = snapshot_and_reset_dispatch_counts()
        fused_total = sum(fused_counts.values())
        assert fused_total <= 2, fused_counts
        # the CI smoke guard: ≥ 3× fewer dispatches than the staged round
        assert staged_total >= 3 * fused_total, (staged_counts, fused_counts)

    def test_per_node_dispatch_comm_metric(self, data):
        from p2pfl_tpu.management.logger import logger

        learner = _learner(data, "metered")
        logger.reset_comm_metrics()
        assert learner.fused_round() is not None
        assert logger.get_comm_metrics("metered").get("device_dispatch") == 1.0


class TestFailureHygiene:
    def test_failed_fused_dispatch_degrades_to_staged(self, data, monkeypatch):
        """A dying fused dispatch must not poison opt state or the rng."""
        learner = _learner(data, "crashy")
        rng_before = learner._rng.bit_generator.state

        def boom(*a, **k):
            # simulate a dispatch that consumed its donated input
            for leaf in jax.tree.leaves(learner.opt_state):
                if isinstance(leaf, jax.Array):
                    leaf.delete()
            raise RuntimeError("XLA mid-dispatch failure")

        import p2pfl_tpu.parallel.spmd as spmd

        monkeypatch.setattr(spmd, "fused_node_round", boom)
        assert learner.fused_round() is None  # degraded, not raised
        assert learner._rng.bit_generator.state == rng_before
        # opt state was rebuilt: the staged fallback trains normally
        monkeypatch.undo()
        learner.fit()
        assert all(
            not leaf.is_deleted()
            for leaf in jax.tree.leaves(learner.opt_state)
            if isinstance(leaf, jax.Array)
        )

    def test_aborted_round_still_flushes_metrics(self, data):
        """A round that trained but dies before RoundFinishedStage must not
        drop its metrics — the workflow's exit flush publishes the stash."""
        from p2pfl_tpu.management.logger import logger
        from p2pfl_tpu.node import Node

        node = Node(learner=_learner(data, "unused-addr", epochs=1))
        node.start()
        try:

            def boom(_n, stage_name):
                if stage_name == "RoundFinishedStage":
                    raise RuntimeError("injected stage failure")

            node.stage_hooks.append(boom)
            node.set_start_learning(rounds=1, epochs=1)
            deadline = time.monotonic() + 60
            time.sleep(0.3)
            while node.learning_active() and time.monotonic() < deadline:
                time.sleep(0.1)
            assert not node.learning_active()
            per_round = logger.get_local_logs().get("experiment", {})
            found = [
                series
                for per_node in per_round.values()
                for addr, metrics in per_node.items()
                if addr == node.addr
                for name, series in metrics.items()
                if name == "train_loss"
            ]
            assert found, "aborted round's train_loss series was dropped"
        finally:
            node.stop()

    def test_spmd_profile_round_restores_rng_on_failure(self, monkeypatch):
        from p2pfl_tpu.parallel.spmd import SpmdFederation

        full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
        fed = SpmdFederation.from_dataset(
            mlp(), full, n_nodes=2, batch_size=64, vote=False, seed=5
        )
        rng_before = fed._rng.bit_generator.state
        monkeypatch.setattr(
            fed, "_profile_round_body", lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("probe died")
            )
        )
        with pytest.raises(RuntimeError):
            fed.profile_round()
        assert fed._rng.bit_generator.state == rng_before

    def test_spmd_failed_round_rebuilds_donated_state(self, monkeypatch):
        import p2pfl_tpu.parallel.spmd as spmd
        from p2pfl_tpu.parallel.spmd import SpmdFederation

        full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
        fed = SpmdFederation.from_dataset(
            mlp(), full, n_nodes=2, batch_size=64, vote=False, seed=5
        )

        def boom(params, opt_state, *a, **k):
            for leaf in jax.tree.leaves((params, opt_state)):
                if isinstance(leaf, jax.Array):
                    leaf.delete()
            raise RuntimeError("mid-dispatch death after donation")

        monkeypatch.setattr(spmd, "spmd_round", boom)
        with pytest.raises(RuntimeError):
            fed.run_round()
        monkeypatch.undo()
        # the store holds live (rebuilt) buffers, not deleted ones...
        assert all(
            not leaf.is_deleted()
            for leaf in jax.tree.leaves((fed.params, fed.opt_state))
            if isinstance(leaf, jax.Array)
        )
        # ...and the federation remains usable
        entry = fed.run_round()
        assert np.isfinite(float(entry["train_loss"]))


class TestWireCompressionAutoSelect:
    def test_auto_selects_by_backend(self, monkeypatch):
        monkeypatch.setattr(Settings, "WIRE_COMPRESSION_DEVICE", None)
        # CPU backend (the test environment): host producer wins
        assert wire_compression_device() is False
        # explicit override beats the auto-select either way
        monkeypatch.setattr(Settings, "WIRE_COMPRESSION_DEVICE", True)
        assert wire_compression_device() is True
        monkeypatch.setattr(Settings, "WIRE_COMPRESSION_DEVICE", False)
        assert wire_compression_device() is False

    def test_auto_select_still_encodes_and_decodes(self, monkeypatch):
        """The resolved flag routes the codec; frames stay cross-decodable."""
        from p2pfl_tpu.learning.weights import decode_params, encode_params

        monkeypatch.setattr(Settings, "WIRE_COMPRESSION_DEVICE", None)
        tree = {"w": jnp.arange(64, dtype=jnp.float32).reshape(8, 8)}
        payload = encode_params(tree, compression="int8")
        flat = decode_params(payload)
        np.testing.assert_allclose(
            np.asarray(flat["w"]), np.asarray(tree["w"]), atol=0.5
        )


class TestFusedFederationE2E:
    def test_two_node_fused_round_converges(self):
        """Full overlay federation on the fused path: rounds complete, both
        nodes hold the identical aggregate, metrics flushed once per round."""
        from p2pfl_tpu.management.logger import logger
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.utils import wait_to_finish

        assert Settings.ROUND_FUSED  # test-settings default
        full = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
        nodes = []
        for i in range(2):
            n = Node(learner=_learner(full.partition(i, 2), f"e2e{i}", epochs=2))
            n.start()
            nodes.append(n)
        try:
            nodes[0].connect(nodes[1].addr)
            time.sleep(0.5)
            logger.reset_comm_metrics()
            reset_dispatch_counts()
            nodes[0].set_start_learning(rounds=2, epochs=2)
            wait_to_finish(nodes, timeout=90)
            # nodes are still running here — harvest atomically so nothing
            # lands between a get and a reset
            counts = snapshot_and_reset_dispatch_counts()
            # 2 nodes × 2 rounds of fused programs, no staged train epochs
            assert counts.get("fused_round") == 4, counts
            assert counts.get("train_epoch") is None, counts
            assert _max_diff(
                nodes[0].learner.get_parameters(),
                nodes[1].learner.get_parameters(),
            ) <= 1e-6
            # batched flush happened: train_loss landed in the local store
            local = logger.get_local_logs()
            found = {
                metric
                for rounds in local.values()
                for per_node in rounds.values()
                for metrics in per_node.values()
                for metric in metrics
            }
            assert "train_loss" in found
        finally:
            for n in nodes:
                n.stop()
