"""Native codec tests: C++ fast path vs numpy fallback, wire integration."""

import numpy as np
import pytest

from p2pfl_tpu import native


def test_native_library_loaded():
    """g++ is in this image — the fast path must actually build."""
    assert native.NATIVE


def test_quantize_roundtrip():
    rng = np.random.default_rng(0)
    x = rng.normal(0, 0.3, size=(64, 33)).astype(np.float32)
    q, scale = native.quantize(x)
    assert q.dtype == np.int8 and q.shape == x.shape
    back = native.dequantize(q, scale)
    assert np.max(np.abs(back - x)) <= scale * 0.51  # half-step rounding error


def test_quantize_matches_fallback():
    rng = np.random.default_rng(1)
    x = rng.normal(size=512).astype(np.float32)
    qn, sn = native.quantize(x)
    # force the python fallback
    lib = native._lib
    try:
        native._lib = None
        qp, sp = native.quantize(x)
    finally:
        native._lib = lib
    assert sn == pytest.approx(sp, rel=1e-6)
    np.testing.assert_array_equal(qn, qp)


def test_crc32c_known_vector():
    # RFC 3720 test vector: CRC32C of 32 zero bytes
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert native.crc32c(b"123456789") == 0xE3069283
    # native and python agree
    assert native.crc32c(b"p2pfl") == native._crc32c_py(b"p2pfl")


def test_wire_codec_int8_roundtrip():
    import jax.numpy as jnp

    from p2pfl_tpu.learning.weights import decode_params, encode_params

    tree = {
        "dense": {"kernel": jnp.linspace(-1, 1, 256).reshape(16, 16), "bias": jnp.zeros(16)},
        "count": jnp.arange(4, dtype=jnp.int32),  # ints must pass through raw
    }
    raw = encode_params(tree, compression="none")
    small = encode_params(tree, compression="int8")
    assert len(small) < len(raw) * 0.5  # 4x on the float tensors

    flat = decode_params(small)  # flat {path: array} keys
    np.testing.assert_array_equal(flat["count"], np.arange(4))
    kernel = np.asarray(tree["dense"]["kernel"])
    err = np.abs(flat["dense/kernel"] - kernel).max()
    assert err < np.abs(kernel).max() / 100  # int8 grid error bound


def test_wire_codec_detects_corruption():
    import jax.numpy as jnp

    from p2pfl_tpu.exceptions import DecodingParamsError
    from p2pfl_tpu.learning.weights import decode_params, encode_params

    payload = bytearray(encode_params({"w": jnp.ones((8, 8))}))
    payload[-3] ^= 0xFF  # flip a tensor byte
    with pytest.raises(DecodingParamsError, match="CRC"):
        decode_params(bytes(payload))
