"""Top-k delta wire compression (``WIRE_COMPRESSION="topk8"``).

Deltas against the round-start global model, top-k by magnitude, int8
values + uint32 indices, anchor digest guarding stale reconstruction, and
error feedback re-injecting dropped coordinates. Beyond-reference
capability (the reference ships raw pickled float32).
"""

import numpy as np
import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.exceptions import AnchorMismatchError
from p2pfl_tpu.learning.weights import (
    anchor_digest,
    decode_params,
    encode_params,
)
from p2pfl_tpu.settings import Settings


@pytest.fixture(autouse=True)
def _settings():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()
    Settings.WIRE_COMPRESSION = "none"
    Settings.TOPK_FRACTION = 0.05
    Settings.TOPK_ERROR_FEEDBACK = True


def _tree(seed=0, shape=(64, 32)):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=shape).astype(np.float32)}


def test_topk_roundtrip_and_shrink():
    anchor = _tree(0)
    delta = np.zeros_like(anchor["w"])
    # a genuinely sparse update: 3% of coordinates moved
    rng = np.random.default_rng(1)
    hot = rng.choice(delta.size, size=delta.size * 3 // 100, replace=False)
    delta.ravel()[hot] = rng.normal(size=hot.size).astype(np.float32)
    params = {"w": anchor["w"] + delta}

    Settings.TOPK_FRACTION = 0.05
    payload = encode_params(params, compression="topk8", anchor=anchor)
    dense = encode_params(params, compression=None)
    assert len(payload) < len(dense) / 6, (len(payload), len(dense))

    flat = decode_params(payload, anchor=anchor)
    # every moved coordinate is inside the kept top-5%, so reconstruction
    # error is pure int8 quantization of the delta
    np.testing.assert_allclose(flat["w"], params["w"], atol=0.02)


def test_topk_anchor_mismatch_detected():
    anchor = _tree(0)
    params = {"w": anchor["w"] + 0.1}
    payload = encode_params(params, compression="topk8", anchor=anchor, anchor_tag="1:2")
    with pytest.raises(AnchorMismatchError, match="no anchor"):
        decode_params(payload)
    # same-tag decode works even against a slightly different anchor (the
    # per-node aggregates legitimately diverge by ~quantization error)...
    decode_params(payload, anchor=anchor, anchor_tag="1:2")
    # ...but a different ROUND's anchor is refused
    with pytest.raises(AnchorMismatchError, match="round mismatch"):
        decode_params(payload, anchor=_tree(9), anchor_tag="1:3")


def test_topk_falls_back_dense_without_anchor():
    params = _tree(2)
    payload = encode_params(params, compression="topk8", anchor=None)
    flat = decode_params(payload)  # i8 fallback needs no anchor
    np.testing.assert_allclose(flat["w"], params["w"], atol=0.05)


def test_error_feedback_recovers_dropped_mass():
    """EF telescopes: residual_T == T·delta − Σ sent_t (each round re-adds
    what previous rounds dropped), so the MEAN transmitted delta converges
    to the true delta — a one-shot top-k loses the residual forever."""
    anchor = _tree(0)
    rng = np.random.default_rng(3)
    delta = rng.normal(size=anchor["w"].shape).astype(np.float32)  # dense delta
    params = {"w": anchor["w"] + delta}
    Settings.TOPK_FRACTION = 0.3

    residual = {}
    sent = []
    for _ in range(4):
        p = encode_params(params, compression="topk8", anchor=anchor, residual=residual)
        sent.append(decode_params(p, anchor=anchor)["w"] - anchor["w"])
    one_shot_err = np.linalg.norm(delta - sent[0])
    mean_err = np.linalg.norm(delta - np.mean(sent, axis=0))
    assert mean_err < one_shot_err * 0.6, (one_shot_err, mean_err)
    # exact bookkeeping: residual_T = T*delta - sum(sent) up to fp rounding
    np.testing.assert_allclose(
        residual["w"].reshape(delta.shape),
        4 * delta - np.sum(sent, axis=0),
        atol=1e-3,
    )


def test_anchor_digest_stability():
    t = _tree(5)
    assert anchor_digest(t) == anchor_digest({"w": t["w"].copy()})
    assert anchor_digest(t) != anchor_digest(_tree(6))


def test_topk_federation_grpc_end_to_end():
    """4-node federation over real gRPC sockets with topk8: payloads shrink
    ~16x vs the dense float32 the reference pickles, and the federation
    still converges."""
    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    Settings.WIRE_COMPRESSION = "topk8"
    Settings.TOPK_FRACTION = 0.2
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = []
    for i in range(4):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 4), batch_size=64)
        node = Node(learner=learner, protocol=GrpcProtocol("127.0.0.1:0"))
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 3, only_direct=True)

    # measure one delta-coded payload vs its dense-int8 twin
    from p2pfl_tpu.utils import check_equal_models

    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=180)
    accs = [n.learner.evaluate()["test_acc"] for n in nodes]
    # two rounds of LOSSY compressed gossip under an arbitrarily loaded
    # host: every node must clearly learn, and the federation as a whole
    # must converge — per-node perfection is gossip-timing noise
    assert min(accs) > 0.5 and float(np.mean(accs)) > 0.65, accs
    # all nodes converge to (approximately — the codec is lossy) one model;
    # catches the round-2 stall a rejected-anchor bug would cause
    check_equal_models(nodes)

    upd = nodes[0].learner.get_model_update()
    assert upd.anchor is not None
    # at the default 5% fraction: 0.05 × (4B idx + 1B val) = 0.25 B/elem,
    # ~4× under dense int8, ~16× under the float32 the reference pickles
    Settings.TOPK_FRACTION = 0.05
    sparse = len(encode_params(upd.params, compression="topk8", anchor=upd.anchor))
    dense8 = len(encode_params(upd.params, compression="int8"))
    assert sparse < dense8 / 3, (sparse, dense8)
    for n in nodes:
        n.stop()


def test_corrupted_tk8_payloads_never_escape_decode_errors():
    """Byte-level corruption of a delta payload must surface as
    DecodingParamsError/AnchorMismatchError — never an arbitrary crash or
    silently wrong tensors (CRC + per-entry length + index-range checks)."""
    from p2pfl_tpu.exceptions import DecodingParamsError

    anchor = _tree(0)
    params = {"w": anchor["w"] + 0.1}
    payload = bytearray(
        encode_params(params, compression="topk8", anchor=anchor, anchor_tag="1:1")
    )
    rng = np.random.default_rng(0)
    for _ in range(60):
        corrupted = bytearray(payload)
        pos = int(rng.integers(len(corrupted)))
        corrupted[pos] ^= int(rng.integers(1, 256))
        try:
            flat = decode_params(bytes(corrupted), anchor=anchor, anchor_tag="1:1")
        except (DecodingParamsError, AnchorMismatchError):
            continue  # detected — good
        # undetected only if the flip was a no-op... it never is (xor>0),
        # so any successful decode means the CRC failed to catch a flip
        raise AssertionError(f"corruption at byte {pos} decoded silently")
    # truncation at every framing boundary
    for cut in (2, 6, len(payload) // 2, len(payload) - 1):
        with pytest.raises((DecodingParamsError, AnchorMismatchError)):
            decode_params(bytes(payload[:cut]), anchor=anchor, anchor_tag="1:1")
