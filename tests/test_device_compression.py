"""Device-side wire compression (``Settings.WIRE_COMPRESSION_DEVICE``).

The fused device producer/consumer (``ops/compression.py``) against the
host numpy baseline: wire-format invariance (one decoder decodes both
producers, host frames stay bit-identical to the pre-device format),
host/device decode parity within the int8 quantization tolerance, the
error-feedback residual living on device across rounds, staleness
pruning, and malformed-payload fuzz for the tk8 path.
"""

import json
import struct

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu import native
from p2pfl_tpu.exceptions import AnchorMismatchError, DecodingParamsError
from p2pfl_tpu.learning.weights import (
    ModelUpdate,
    PayloadCache,
    _frame,
    decode_params,
    encode_params,
    reset_wire_stats,
    wire_stats,
)
from p2pfl_tpu.settings import Settings


@pytest.fixture(autouse=True)
def _settings():
    yield
    Settings.WIRE_COMPRESSION = "none"
    Settings.WIRE_COMPRESSION_DEVICE = True
    Settings.TOPK_FRACTION = 0.05
    Settings.TOPK_ERROR_FEEDBACK = True


def _tree(seed=0):
    """Mixed tree: big/medium float leaves (topk path), a tiny float leaf
    (dense-i8 under topk8), and an int leaf (raw passthrough)."""
    rng = np.random.default_rng(seed)
    return {
        "layer0/w": rng.normal(size=(64, 32)).astype(np.float32),
        "layer1/w": rng.normal(size=(300,)).astype(np.float32),
        "tiny/b": rng.normal(size=(10,)).astype(np.float32),
        "steps": np.arange(5, dtype=np.int32),
    }


def _to_device(tree):
    return {k: jnp.asarray(v) for k, v in tree.items()}


def _anchor_of(tree):
    return {
        k: (v - 0.01 if np.dtype(v.dtype).kind == "f" else v) for k, v in tree.items()
    }


def _assert_trees_close(a, b, atol):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_allclose(
            np.asarray(a[k], np.float32), np.asarray(b[k], np.float32), atol=atol
        )


# ---- wire-format invariance ----


@pytest.mark.parametrize("comp", ["int8", "topk8"])
def test_cross_producer_frames_decode_with_one_decoder(comp):
    """Device-encoded payloads decode with the unchanged host consumer and
    host-encoded payloads with the device consumer — same decoder function,
    same tolerances, no producer dialect."""
    params = _tree(0)
    anchor = _anchor_of(params)
    kw = {"compression": comp}
    if comp == "topk8":
        kw.update(anchor=anchor, anchor_tag="1:1")

    Settings.WIRE_COMPRESSION_DEVICE = False
    host_payload = encode_params(params, **kw)
    kw_dev = dict(kw)
    if comp == "topk8":
        kw_dev["anchor"] = _to_device(anchor)
    Settings.WIRE_COMPRESSION_DEVICE = True
    device_payload = encode_params(_to_device(params), **kw_dev)

    dkw = {"anchor": anchor, "anchor_tag": "1:1"} if comp == "topk8" else {}
    dkw_dev = (
        {"anchor": _to_device(anchor), "anchor_tag": "1:1"} if comp == "topk8" else {}
    )
    # host consumer × both producers
    Settings.WIRE_COMPRESSION_DEVICE = False
    ref = decode_params(host_payload, **dkw)
    cross = decode_params(device_payload, **dkw)
    _assert_trees_close(ref, cross, atol=0.05)
    _assert_trees_close(ref, params, atol=0.05)
    # device consumer × both producers (anchor device-resident)
    Settings.WIRE_COMPRESSION_DEVICE = True
    dev_ref = decode_params(host_payload, **dkw_dev)
    dev_cross = decode_params(device_payload, **dkw_dev)
    _assert_trees_close(ref, dev_ref, atol=0.05)
    _assert_trees_close(ref, dev_cross, atol=0.05)
    if comp == "topk8":
        # the device consumer's reconstruction never left the device
        assert isinstance(dev_ref["layer0/w"], jax.Array)
    # raw (non-float) leaves are bit-preserved by both producers
    np.testing.assert_array_equal(np.asarray(cross["steps"]), params["steps"])


def test_host_path_bit_identical_to_reference_frames():
    """``WIRE_COMPRESSION_DEVICE=False`` must emit byte-for-byte the frames
    the pre-device codec produced. The reference encoder below is a frozen
    copy of that algorithm — any framing/ordering/scale drift in the host
    producer fails this, on any backend (it reuses the same native
    quantize/CRC the production path uses)."""

    def reference_encode(tree, compression, anchor=None, anchor_tag=None, residual=None):
        flat = {k: np.asarray(v) for k, v in tree.items()}
        anchor_flat = (
            {k: np.asarray(v) for k, v in anchor.items()} if anchor is not None else None
        )
        entries, buffers, crc = [], [], 0
        for key in sorted(flat):
            arr = flat[key]
            entry = {"k": key, "shape": list(arr.shape), "dtype": arr.dtype.name}
            use_topk = (
                compression == "topk8"
                and arr.dtype.kind == "f"
                and anchor_flat is not None
                and key in anchor_flat
                and arr.size > 16
            )
            if use_topk:
                delta = np.asarray(arr, np.float32).ravel() - np.asarray(
                    anchor_flat[key], np.float32
                ).ravel()
                if residual is not None and key in residual:
                    delta = delta + residual[key]
                k = max(1, int(np.ceil(arr.size * Settings.TOPK_FRACTION)))
                idx = np.argpartition(np.abs(delta), -k)[-k:].astype(np.uint32)
                idx.sort()
                q, scale = native.quantize(delta[idx])
                if residual is not None:
                    sent = np.zeros_like(delta)
                    sent[idx] = native.dequantize(q, scale)
                    residual[key] = delta - sent
                bufs = (idx.tobytes(), q.tobytes())
                entry.update(enc="tk8", scale=scale, nnz=int(k))
            elif compression in ("int8", "topk8") and arr.dtype.kind == "f":
                q, scale = native.quantize(np.asarray(arr, dtype=np.float32))
                bufs = (q.tobytes(),)
                entry.update(enc="i8", scale=scale)
            else:
                bufs = (np.ascontiguousarray(arr).tobytes(),)
            entry["n"] = sum(len(b) for b in bufs)
            for b in bufs:
                crc = native.crc32c(b, crc)
                buffers.append(b)
            entries.append(entry)
        head = {"v": 1, "t": entries, "crc": crc}
        if any(e.get("enc") == "tk8" for e in entries):
            head["anchor_tag"] = anchor_tag if anchor_tag is not None else ""
        header = json.dumps(head).encode("utf-8")
        return b"P2TW" + struct.pack("<I", len(header)) + header + b"".join(buffers)

    Settings.WIRE_COMPRESSION_DEVICE = False
    params = _tree(3)
    anchor = _anchor_of(params)
    assert encode_params(params, compression="none") == reference_encode(params, "none")
    assert encode_params(params, compression="int8") == reference_encode(params, "int8")
    res_now, res_ref = {}, {}
    got = encode_params(
        params, compression="topk8", anchor=anchor, anchor_tag="2:7", residual=res_now
    )
    want = reference_encode(params, "topk8", anchor=anchor, anchor_tag="2:7", residual=res_ref)
    assert got == want
    for k in res_ref:
        np.testing.assert_array_equal(res_now[k], res_ref[k])


# ---- error feedback on device ----


def test_error_feedback_device_residual_across_rounds():
    """≥3 rounds of device encode: the residual store carries DEVICE arrays
    between rounds, and error feedback telescopes exactly like the host
    path (mean transmitted delta converges to the true delta)."""
    Settings.WIRE_COMPRESSION_DEVICE = True
    Settings.TOPK_FRACTION = 0.3
    anchor_np = _tree(0)
    rng = np.random.default_rng(3)
    delta = rng.normal(size=anchor_np["layer0/w"].shape).astype(np.float32)
    params_np = dict(anchor_np)
    params_np["layer0/w"] = anchor_np["layer0/w"] + delta
    params, anchor = _to_device(params_np), _to_device(anchor_np)

    residual = {}
    sent = []
    for _ in range(4):
        payload = encode_params(
            params, compression="topk8", anchor=anchor, anchor_tag="0:0", residual=residual
        )
        # device-resident carry: no np.ndarray ever enters the store
        assert all(isinstance(v, jax.Array) for v in residual.values())
        flat = decode_params(payload, anchor=anchor, anchor_tag="0:0")
        sent.append(np.asarray(flat["layer0/w"], np.float32) - anchor_np["layer0/w"])
    one_shot = np.linalg.norm(delta - sent[0])
    mean_err = np.linalg.norm(delta - np.mean(sent, axis=0))
    assert mean_err < one_shot * 0.6, (one_shot, mean_err)
    # exact bookkeeping: residual_T = T·delta − Σ sent_t up to fp rounding
    np.testing.assert_allclose(
        np.asarray(residual["layer0/w"]).reshape(delta.shape),
        4 * delta - np.sum(sent, axis=0),
        atol=1e-3,
    )


def test_host_device_error_feedback_parity():
    """Host and device EF runs from identical state transmit statistically
    identical mass (same telescoping sum, within quantization-tie noise)."""
    Settings.TOPK_FRACTION = 0.25
    anchor_np = _tree(1)
    rng = np.random.default_rng(7)
    # distinct |delta| everywhere: tie-breaking between argpartition and
    # top_k is the one legitimate divergence, so keep ties out of the test
    params_np = {
        k: (v + rng.normal(scale=0.05, size=v.shape).astype(np.float32)
            if np.dtype(v.dtype).kind == "f" else v)
        for k, v in anchor_np.items()
    }
    totals = {}
    for mode, flag in (("host", False), ("device", True)):
        Settings.WIRE_COMPRESSION_DEVICE = flag
        tree = _to_device(params_np) if flag else params_np
        anc = _to_device(anchor_np) if flag else anchor_np
        residual = {}
        acc = np.zeros_like(anchor_np["layer0/w"])
        for _ in range(3):
            payload = encode_params(
                tree, compression="topk8", anchor=anc, anchor_tag="0:0", residual=residual
            )
            Settings.WIRE_COMPRESSION_DEVICE = False  # decode via host consumer
            flat = decode_params(payload, anchor=anchor_np, anchor_tag="0:0")
            Settings.WIRE_COMPRESSION_DEVICE = flag
            acc += np.asarray(flat["layer0/w"], np.float32) - anchor_np["layer0/w"]
        totals[mode] = acc
    np.testing.assert_allclose(totals["host"], totals["device"], atol=0.01)


# ---- residual staleness (satellite) ----


def test_stale_residual_entries_dropped_not_crashed():
    Settings.WIRE_COMPRESSION_DEVICE = False
    params = _tree(2)
    anchor = _anchor_of(params)
    residual = {
        "layer0/w": np.zeros(999, np.float32),  # wrong size: tensor reshaped
        "ghost/w": np.zeros(64, np.float32),  # key no longer exists
        "tiny/b": np.zeros(10, np.float32),  # off the topk path (too small)
        "layer1/w": np.full(300, 0.5, np.float32),  # valid — must survive
    }
    payload = encode_params(
        params, compression="topk8", anchor=anchor, anchor_tag="0:0", residual=residual
    )
    decode_params(payload, anchor=anchor, anchor_tag="0:0")
    assert set(residual) == {"layer0/w", "layer1/w"}  # stale entries pruned
    # the valid entry was folded (residual got rewritten by the encode)
    assert not np.allclose(np.asarray(residual["layer1/w"]), 0.5)


def test_residual_survives_producer_flips():
    """host → device → host encodes share one residual store: each producer
    normalizes the other's arrays instead of crashing or dropping them."""
    params_np = _tree(4)
    anchor_np = _anchor_of(params_np)
    params, anchor = _to_device(params_np), _to_device(anchor_np)
    residual = {}
    for flag, tree, anc in (
        (False, params_np, anchor_np),
        (True, params, anchor),
        (False, params_np, anchor_np),
    ):
        Settings.WIRE_COMPRESSION_DEVICE = flag
        payload = encode_params(
            tree, compression="topk8", anchor=anc, anchor_tag="0:0", residual=residual
        )
        flat = decode_params(payload, anchor=anchor_np, anchor_tag="0:0")
        _assert_trees_close(flat, params_np, atol=0.05)
    # a compression-mode flip prunes the whole store (keys left the topk path)
    encode_params(params_np, compression="int8", anchor=None, residual=residual)
    assert residual == {}


# ---- malformed tk8 payload fuzz (satellite) ----


def _tk8_frame(key, shape, idx, q, scale, nnz, anchor_tag="0:0"):
    """Hand-build a tk8 frame with a VALID CRC so decode exercises the
    structural validators, not the checksum."""
    idx = np.asarray(idx, np.uint32)
    q = np.asarray(q, np.int8)
    entry = {
        "k": key,
        "shape": list(shape),
        "dtype": "float32",
        "enc": "tk8",
        "scale": float(scale),
        "nnz": int(nnz),
    }
    return _frame([(entry, (idx.tobytes(), q.tobytes()))], anchor_tag)


@pytest.mark.parametrize("device", [False, True])
def test_malformed_tk8_payloads_rejected(device):
    Settings.WIRE_COMPRESSION_DEVICE = device
    anchor_np = {"w": np.zeros((8, 8), np.float32)}
    anchor = _to_device(anchor_np) if device else anchor_np
    dk = {"anchor": anchor, "anchor_tag": "0:0"}

    ok = _tk8_frame("w", (8, 8), [1, 5, 9], [10, -20, 30], 0.01, 3)
    np.testing.assert_allclose(
        np.asarray(decode_params(ok, **dk)["w"]).ravel()[[1, 5, 9]],
        [0.1, -0.2, 0.3],
        atol=1e-6,
    )
    # duplicate indices: the device scatter-ADD would double-apply where the
    # host write-wins — must be rejected, not silently divergent
    with pytest.raises(DecodingParamsError, match="duplicate or unsorted"):
        decode_params(_tk8_frame("w", (8, 8), [1, 5, 5], [1, 2, 3], 0.01, 3), **dk)
    with pytest.raises(DecodingParamsError, match="duplicate or unsorted"):
        decode_params(_tk8_frame("w", (8, 8), [9, 5, 1], [1, 2, 3], 0.01, 3), **dk)
    with pytest.raises(DecodingParamsError, match="out of range"):
        decode_params(_tk8_frame("w", (8, 8), [1, 5, 64], [1, 2, 3], 0.01, 3), **dk)
    # nnz lies about the buffer length
    with pytest.raises(DecodingParamsError, match="inconsistent header"):
        decode_params(_tk8_frame("w", (8, 8), [1, 5, 9], [1, 2, 3], 0.01, 7), **dk)
    # nnz > tensor size cannot carry strictly-ascending in-range indices
    with pytest.raises(DecodingParamsError):
        decode_params(
            _tk8_frame("w", (2,), [0, 1, 1], [1, 2, 3], 0.01, 3),
            anchor={"w": (jnp.zeros(2) if device else np.zeros(2, np.float32))},
            anchor_tag="0:0",
        )
    # missing anchor tensor for a delta-coded key
    with pytest.raises(AnchorMismatchError, match="no anchor tensor"):
        decode_params(
            _tk8_frame("nope", (8, 8), [1], [5], 0.01, 1), **dk
        )


# ---- observability (satellite) ----


def test_wire_byte_counters_per_node_and_process():
    from p2pfl_tpu.management.logger import logger

    logger.reset_comm_metrics()
    reset_wire_stats()
    Settings.WIRE_COMPRESSION = "topk8"
    Settings.WIRE_COMPRESSION_DEVICE = True
    params = _to_device(_tree(0))
    cache = PayloadCache(owner="nodeA:1")
    upd = ModelUpdate(
        params,
        ["nodeA:1"],
        1,
        anchor=_to_device(_anchor_of(_tree(0))),
        anchor_tag="0:0",
        payload_cache=cache,
        cache_version=1,
    )
    upd.cache_round = 0
    payload = upd.encode()
    assert upd.encode() is payload  # second call: cache, no new counters

    m = logger.get_comm_metrics("nodeA:1")
    assert m["wire_encode_device"] == 1 and "wire_encode_host" not in m
    assert m["wire_payload_bytes"] == len(payload)
    assert m["wire_raw_bytes"] > m["wire_payload_bytes"] > m["wire_d2h_bytes"] * 0.2
    # D2H carried ~the compressed bytes, not the raw model
    assert m["wire_d2h_bytes"] < m["wire_raw_bytes"] / 4
    s = wire_stats()
    assert s["device_encodes"] >= 1 and s["payload_bytes"] >= len(payload)
    Settings.WIRE_COMPRESSION = "none"


def test_payload_cache_key_includes_producer_flag():
    Settings.WIRE_COMPRESSION = "int8"
    params = _to_device(_tree(0))
    cache = PayloadCache(owner="n")

    def fresh():
        u = ModelUpdate(params, ["n"], 1, payload_cache=cache, cache_version=7)
        u.cache_round = 0
        return u

    Settings.WIRE_COMPRESSION_DEVICE = True
    a = fresh().encode()
    Settings.WIRE_COMPRESSION_DEVICE = False
    b = fresh().encode()
    # flipping the producer may NOT replay the other producer's bytes
    assert cache.misses == 2, (cache.hits, cache.misses)
    decode_ref = decode_params(a)
    _assert_trees_close(decode_ref, decode_params(b), atol=0.05)
    Settings.WIRE_COMPRESSION = "none"


def test_scalar_pytree_leaves_still_encode():
    """Python-scalar leaves (no .dtype) are normalized like the old
    ``_flatten_named`` path did — both producers, all modes."""
    tree = {"w": np.ones(32, np.float32), "lr": 0.1, "step": 3}
    anchor = {"w": np.ones(32, np.float32) * 0.99, "lr": 0.1, "step": 3}
    for flag in (False, True):
        Settings.WIRE_COMPRESSION_DEVICE = flag
        for comp, kw in (
            ("none", {}),
            ("int8", {}),
            ("topk8", {"anchor": anchor, "anchor_tag": "0:0"}),
        ):
            payload = encode_params(tree, compression=comp, **kw)
            dk = {"anchor": anchor, "anchor_tag": "0:0"} if comp == "topk8" else {}
            flat = decode_params(payload, **dk)
            assert float(np.asarray(flat["lr"])) == pytest.approx(0.1, abs=1e-3)
            assert int(np.asarray(flat["step"])) == 3


# ---- gossiper lazy payload resolution ----


def test_gossiper_resolves_lazy_payloads_on_calling_thread():
    from p2pfl_tpu.communication.gossiper import Gossiper

    sent = []
    g = Gossiper("me", lambda nei, env, create_connection=False: sent.append((nei, env)) or True)
    built = []

    def make(nei, value):
        def build():
            built.append(nei)
            return value

        return build

    # pool not started → sequential path; callables resolve, None declines
    results, skipped = g._dispatch_sends(
        [("a", make("a", "payload-a")), ("b", make("b", None)), ("c", "eager")]
    )
    assert built == ["a", "b"]
    assert sent == [("a", "payload-a"), ("c", "eager")]
    assert results == [True, None, True]
    assert skipped == []
