"""Async bounded-staleness federation (ISSUE 9).

Four layers, mirroring the subsystem's structure:

- staleness math + version-vector dedup (pure units — including dedup
  under the fault layer's DUPLICATE delivery semantics),
- the BufferedAggregator's merge algebra against a numpy reference,
- determinism: the same seed + fault plan replays a simulated fleet
  bit-identically, and a 1k-node hierarchical fleet completes an
  end-to-end convergence drive with no round barrier,
- real nodes: an async federation over the in-memory transport (flat and
  hierarchical) finishing under drop + slow + crash chaos.
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    CrashSpec,
    EdgeFault,
    FaultInjector,
    FaultPlan,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.grpc_transport import decode_weights, encode_weights
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.communication.message import WeightsEnvelope
from p2pfl_tpu.federation import (
    BufferedAggregator,
    HierarchicalTopology,
    SimulatedAsyncFleet,
    VersionVector,
    staleness_weight,
)
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    yield
    Settings.FEDERATION_MODE = "sync"
    Settings.HIER_CLUSTER_SIZE = 0
    MemoryRegistry.reset()


def _update(value, contributors, num_samples=1, version=None, dim=4):
    upd = ModelUpdate({"w": np.full(dim, value, np.float32)}, list(contributors), num_samples)
    upd.version = version
    return upd


# ---------------------------------------------------------------------------
# staleness weight math
# ---------------------------------------------------------------------------


def test_staleness_weight_math():
    # w(0) = 1 at any alpha; monotone decreasing in tau; alpha controls decay
    for alpha in (0.0, 0.5, 1.0, 2.0):
        assert staleness_weight(0, alpha) == 1.0
    taus = [staleness_weight(t, 0.5) for t in range(10)]
    assert all(a > b for a, b in zip(taus, taus[1:]))
    assert staleness_weight(3, 1.0) == pytest.approx(1 / 4)
    assert staleness_weight(3, 0.5) == pytest.approx(1 / 2)
    assert staleness_weight(8, 2.0) == pytest.approx(1 / 81)
    # alpha=0 disables down-weighting entirely
    assert staleness_weight(1000, 0.0) == 1.0
    # negative tau (merging tier lagging the producer) clamps to fresh
    assert staleness_weight(-3, 0.5) == 1.0


def test_version_vector_dedup_and_reorder():
    vv = VersionVector()
    assert vv.observe("a", 1)
    assert not vv.observe("a", 1), "exact duplicate accepted"
    # out-of-order AHEAD is accepted (seq 2 lost on the wire), then the
    # late straggler is rejected as superseded
    assert vv.observe("a", 3)
    assert not vv.observe("a", 2), "superseded seq accepted after a newer one"
    assert vv.last("a") == 3
    # origins are independent
    assert vv.observe("b", 1)
    vv.merge({"a": 10, "c": 2})
    assert vv.last("a") == 10 and vv.last("c") == 2
    vv.merge({"a": 5})  # monotone: merge never regresses
    assert vv.last("a") == 10


# ---------------------------------------------------------------------------
# BufferedAggregator: merge algebra, dedup, bounded staleness
# ---------------------------------------------------------------------------


def test_buffer_merge_matches_numpy_reference():
    """K staleness-weighted updates merge to the closed-form weighted
    average (alpha and sample counts both active), mixed by server_lr."""
    alpha, lr = 1.0, 0.5
    start = np.full(4, 10.0, np.float32)
    buf = BufferedAggregator(
        "me", {"w": start.copy()}, k=3, alpha=alpha, server_lr=lr, max_staleness=16
    )
    # advance the global twice so offered updates carry real staleness
    buf.set_global({"w": start.copy()}, 2)
    entries = [  # (value, samples, base_version) → tau = 2 - base
        (1.0, 2, 2),  # tau 0, w = 2·1
        (4.0, 1, 1),  # tau 1, w = 1·(1/2)
        (7.0, 3, 0),  # tau 2, w = 3·(1/3) = 1
    ]
    for i, (val, ns, base) in enumerate(entries):
        res = buf.offer(_update(val, [f"n{i}"], ns, version=(f"n{i}", 1, base)))
    assert res is not None
    weights = np.array([2 * 1.0, 1 * 0.5, 3 * (1 / 3)], np.float32)
    avg = (weights * np.array([1.0, 4.0, 7.0], np.float32)).sum() / weights.sum()
    expect = (1 - lr) * 10.0 + lr * avg
    np.testing.assert_allclose(np.asarray(res.params["w"]), expect, rtol=1e-6)
    assert res.version == 3  # set_global took it to 2, the flush minted 3
    assert res.contributors == ["n0", "n1", "n2"]
    assert sorted(res.taus) == [0, 1, 2]


def test_buffer_dedup_under_fault_plan_duplicate_delivery():
    """FaultPlan duplicate semantics end to end: a duplicated weights
    envelope is re-delivered verbatim (faults.py _stale_copy) — the
    version vector must reject the copy, so K counts distinct updates."""
    buf = BufferedAggregator("me", {"w": np.zeros(4, np.float32)}, k=3, alpha=0.0)
    delivered = []

    def transport(nei, env, create_connection=False):
        delivered.append(env)
        buf.offer(env.update)
        return True

    plan = FaultPlan(seed=5, default=EdgeFault(duplicate=1.0, duplicate_delay=0.02))
    inj = FaultInjector(plan, "src")
    for i in range(2):
        upd = _update(float(i), [f"n{i}"], version=(f"n{i}", 1, 0))
        env = WeightsEnvelope("src", 0, "async_update", upd)
        assert inj("dst", env, False, transport)
    deadline = time.monotonic() + 2.0
    while len(delivered) < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(delivered) == 4, "duplicate copies never delivered"
    # 4 deliveries, but only 2 DISTINCT updates are buffered: no flush at
    # k=3, and the metrics name the two replays
    assert buf.pending() == 2
    m = logger.get_comm_metrics("me")
    assert m.get("async_dup_drop", 0) == 2
    assert m.get("async_update_buffered", 0) == 2


def test_buffer_bounded_staleness_drop():
    buf = BufferedAggregator(
        "me", {"w": np.zeros(4, np.float32)}, k=2, alpha=0.5, max_staleness=3
    )
    buf.set_global({"w": np.zeros(4, np.float32)}, 10)
    # tau = 10 - 6 = 4 > 3: dropped, not merged at a vanishing weight
    assert buf.offer(_update(1.0, ["a"], version=("a", 1, 6))) is None
    assert buf.pending() == 0
    assert logger.get_comm_metrics("me").get("async_stale_drop", 0) == 1
    # tau = 3 is still within the bound
    assert buf.offer(_update(1.0, ["a"], version=("a", 2, 7))) is None
    assert buf.pending() == 1


def test_buffer_set_k_repair_flushes_blocked_buffer():
    """The eviction-repair hook: a dead member leaves the buffer one
    short of K forever — shrinking K to the live fan-in fires the merge
    it was blocking."""
    buf = BufferedAggregator("me", {"w": np.zeros(4, np.float32)}, k=3, alpha=0.0)
    buf.offer(_update(2.0, ["a"], version=("a", 1, 0)))
    assert buf.offer(_update(4.0, ["b"], version=("b", 1, 0))) is None
    res = buf.set_k(2)
    assert res is not None and res.version == 1
    np.testing.assert_allclose(np.asarray(res.params["w"]), 3.0)


def test_flush_order_is_arrival_order_independent():
    """The determinism contract: within one buffer window the fold order
    is (origin, seq)-sorted, so two arrival interleavings of the same
    updates produce bit-identical merges."""

    def run(order):
        buf = BufferedAggregator(
            "me", {"w": np.arange(4, dtype=np.float32)}, k=3, alpha=0.5
        )
        buf.set_global({"w": np.arange(4, dtype=np.float32)}, 1)
        ups = {
            "a": _update(1.25, ["a"], 2, version=("a", 1, 0)),
            "b": _update(-3.5, ["b"], 1, version=("b", 1, 1)),
            "c": _update(0.75, ["c"], 3, version=("c", 1, 1)),
        }
        res = None
        for key in order:
            res = buf.offer(ups[key])
        return np.asarray(res.params["w"])

    first = run(["a", "b", "c"])
    for order in (["c", "a", "b"], ["b", "c", "a"]):
        np.testing.assert_array_equal(first, run(order))


# ---------------------------------------------------------------------------
# topology
# ---------------------------------------------------------------------------


def test_hierarchical_topology_deterministic_and_connected():
    members = [f"n{i:03d}" for i in range(37)]
    import random as _random

    shuffled = list(members)
    _random.Random(0).shuffle(shuffled)
    t1 = HierarchicalTopology(members, 8)
    t2 = HierarchicalTopology(shuffled, 8)  # order-independent derivation
    assert t1.clusters == t2.clusters and t1.global_root == t2.global_root
    # every member reaches the global root in <= 2 hops; children/parent agree
    for m in members:
        hops, cur = 0, m
        while t1.parent_of(cur) is not None:
            parent = t1.parent_of(cur)
            assert cur in t1.children_of(parent) or t1.aggregator_for(cur) == cur
            cur = parent
            hops += 1
        assert cur == t1.global_root and hops <= 2
    # no singleton trailing cluster (folded into the previous one)
    assert all(len(c) >= 2 for c in t1.clusters)
    # flat collapse
    flat = HierarchicalTopology(members, 0)
    assert flat.is_flat() and flat.regionals == [flat.global_root]


# ---------------------------------------------------------------------------
# wire: the optional "vv" field
# ---------------------------------------------------------------------------


def test_wire_version_roundtrip_and_old_frame_compat():
    upd = ModelUpdate({"w": np.ones(3, np.float32)}, ["a"], 2)
    upd.version = ("a", 7, 3)
    env = WeightsEnvelope("a", 0, "async_update", upd)
    out = decode_weights(encode_weights(env))
    assert out.update.version == ("a", 7, 3)
    # a sync-plane frame (no version) decodes with version None — and a
    # pre-PR frame never carries the key at all
    upd2 = ModelUpdate({"w": np.ones(3, np.float32)}, ["a"], 2)
    env2 = WeightsEnvelope("a", 0, "add_model", upd2)
    raw = encode_weights(env2)
    assert b'"vv"' not in raw
    assert decode_weights(raw).update.version is None


# ---------------------------------------------------------------------------
# simulated fleet: determinism + 1k-node hierarchical convergence
# ---------------------------------------------------------------------------


def _chaos_plan(n, seed=1905):
    """10% slow / ~1% crash over the simulated addresses, plus a lossy wire."""
    addrs = [f"sim-{i:04d}" for i in range(n)]
    slow = {a: 0.5 for a in addrs[::10][: max(1, n // 10)]}  # every 10th
    crashes = {
        a: CrashSpec(stage="AsyncTrainStage", round_no=2)
        for a in addrs[5::100][: max(1, n // 100)]  # offset: disjoint from slow
    }
    return FaultPlan(
        seed=seed,
        default=EdgeFault(drop=0.02, duplicate=0.05, duplicate_delay=0.3),
        slow_nodes=slow,
        crashes=crashes,
    )


def test_simfleet_same_seed_and_plan_replays_bit_identical():
    def run():
        return SimulatedAsyncFleet(
            64,
            seed=42,
            cluster_size=8,
            updates_per_node=4,
            slow_frac=0.1,
            slow_factor=8.0,
            plan=_chaos_plan(64, seed=1905),
        ).run()

    a, b = run(), run()
    assert a.version == b.version and a.version > 0
    np.testing.assert_array_equal(np.asarray(a.params["w"]), np.asarray(b.params["w"]))
    assert a.loss_curve == b.loss_curve  # exact floats, exact virtual times
    assert a.updates_dropped_wire == b.updates_dropped_wire
    assert a.duplicates_injected == b.duplicates_injected
    assert a.crashed == b.crashed
    # a different seed diverges (the test has teeth)
    c = SimulatedAsyncFleet(
        64, seed=43, cluster_size=8, updates_per_node=4, slow_frac=0.1,
        slow_factor=8.0, plan=_chaos_plan(64, seed=1905),
    ).run()
    assert not np.array_equal(np.asarray(a.params["w"]), np.asarray(c.params["w"]))


def test_simfleet_1k_hierarchical_converges_without_round_barrier():
    """ISSUE 9 acceptance: a 1k-node hierarchical fleet completes an
    end-to-end convergence drive, and its makespan tracks the MEDIAN
    node, not the straggler: with 10% of nodes 20× slower, a
    barrier-synchronized fleet would take ≥ budget × slow duration."""
    n, budget, slow_factor = 1000, 4, 20.0
    fleet = SimulatedAsyncFleet(
        n,
        seed=7,
        cluster_size=32,
        updates_per_node=budget,
        base_duration=1.0,
        slow_frac=0.10,
        slow_factor=slow_factor,
        local_lr=0.7,
    )
    res = fleet.run()
    assert res.version > 10, "global model barely advanced"
    assert res.merges == res.version
    # convergence: the consensus loss fell by >10x from the cold start
    start_loss = fleet.loss_fn({"w": np.zeros_like(np.asarray(res.params["w"]))})
    assert res.final_loss() < start_loss / 10
    # no round barrier: a sync fleet's rounds are gated by the slowest
    # node (≈ budget × 0.8·base×slow_factor at minimum); the async fleet's
    # healthy majority finished its whole budget well before that
    sync_floor = budget * 0.8 * slow_factor
    healthy_done = [
        t for t, _v, _l in res.loss_curve if t < sync_floor / 2
    ]
    assert healthy_done, "no merges landed before the sync floor"
    assert res.time_to_target is None or res.time_to_target < sync_floor
    # the staleness histogram saw real spread (slow nodes merge late)
    from p2pfl_tpu.management.telemetry import telemetry

    hists = telemetry.value_histograms()
    stale = [v for k, v in hists.items() if k.endswith("/staleness") and v.get("count")]
    assert stale, "no staleness observations recorded"


# ---------------------------------------------------------------------------
# real nodes: async federation over the in-memory transport
# ---------------------------------------------------------------------------


def _mk_nodes(n):
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(n)]
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, n - 1, only_direct=True, wait=10)
    return nodes


def _stop_all(nodes):
    for n in nodes:
        n.stop()


def _sum_metric(metric):
    return sum(d.get(metric, 0.0) for d in logger.get_comm_metrics().values())


def test_async_federation_flat_e2e():
    """4 nodes, flat FedBuff: every update merges (stash covers the
    context race), everyone ends on the same final global version."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 0
    nodes = _mk_nodes(4)
    try:
        nodes[0].set_start_learning(rounds=3, epochs=1)
        wait_to_finish(nodes, timeout=40)
        assert _sum_metric("async_merge") >= 3
        assert _sum_metric("async_model_adopt") >= 1
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in nodes]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-6)
        # a second experiment on the same overlay works (state cleared)
        nodes[1].set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=30, min_experiments=2)
    finally:
        _stop_all(nodes)


def test_async_federation_hierarchical_chaos():
    """ISSUE 9 acceptance (threaded half): 6 nodes in 2 clusters under
    5% drop + slow peer + mid-run edge crash — survivors finish their
    budgets, merges happen at both tiers, and the fleet ends converged
    on one global, well inside the drain/timeout ceilings."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 3
    nodes = _mk_nodes(6)
    victim, slow = nodes[4], nodes[5]
    plan = FaultPlan(
        seed=1905,
        default=EdgeFault(drop=0.05),
        slow_nodes={slow.addr: 0.2},
        crashes={victim.addr: CrashSpec(stage="AsyncTrainStage", round_no=1)},
    )
    install_fault_plan(nodes, plan)
    survivors = [n for n in nodes if n is not victim]
    try:
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=3, epochs=1)
        wait_to_finish(survivors, timeout=45)
        elapsed = time.monotonic() - t0
        assert elapsed < 40.0
        assert not victim._running
        for n in survivors:
            assert n.state.round is None
        assert _sum_metric("async_merge") >= 2
        assert _sum_metric("fault_crash") == 1
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        remove_fault_plan(nodes)
        _stop_all(nodes)


def test_async_regional_crash_fails_over_to_root():
    """A dead REGIONAL must not orphan its cluster: once eviction lands,
    every node re-derives the topology with the corpse as a hole
    (federation/routing.py) — the cluster's next-sorted live member
    self-elects as successor regional (seeding its buffer from its last
    adopted global), and until each edge observes the death its updates
    are absorbed rather than lost — so the cluster keeps merging and
    keeps receiving fresh globals."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 3
    nodes = _mk_nodes(6)
    # members sort node-1..node-6 → clusters [1,2,3], [4,5,6]; node-4 is
    # the non-root regional — crash IT mid-run
    by_addr = {n.addr: n for n in nodes}
    regional = by_addr[sorted(by_addr)[3]]
    plan = FaultPlan(
        seed=1905,
        crashes={regional.addr: CrashSpec(stage="AsyncTrainStage", round_no=1)},
    )
    install_fault_plan(nodes, plan)
    survivors = [n for n in nodes if n is not regional]
    try:
        nodes[0].set_start_learning(rounds=4, epochs=1)
        wait_to_finish(survivors, timeout=60)
        assert not regional._running
        # the orphaned cluster's edges still ended on the fleet's final
        # global (root adopted them), and merges continued after the crash
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
        assert _sum_metric("async_merge") >= 2
    finally:
        remove_fault_plan(nodes)
        _stop_all(nodes)


def test_async_rejects_unsupported_compositions():
    """secagg and topk8 abort the async experiment loudly at start."""
    Settings.FEDERATION_MODE = "async"
    nodes = _mk_nodes(2)
    try:
        old = Settings.SECURE_AGGREGATION
        Settings.SECURE_AGGREGATION = True
        try:
            nodes[0].set_start_learning(rounds=1, epochs=1)
            deadline = time.monotonic() + 10
            while nodes[0].learning_active() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert nodes[0].state.round is None
            assert nodes[0]._running
        finally:
            Settings.SECURE_AGGREGATION = old
    finally:
        _stop_all(nodes)
