"""Shard-native ICI weights plane (ISSUE 13).

Runs on the 8-virtual-device CPU mesh (conftest): the pure-XLA
``ppermute`` backend is the CPU-runnable bit-parity fallback, so the
transfer primitive, the zero-host-bytes federation contract, per-peer
degradation and the chaos composition are all exercised without TPU
hardware. The Pallas remote-DMA backend shares every line of this module
except the exchange body (``parallel/ici_plane.py``), so what is pinned
here pins the routing/fault/telemetry machinery for both.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.communication import ici
from p2pfl_tpu.communication.faults import (
    CrashSpec,
    EdgeFault,
    FaultPlan,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.grpc_transport import decode_weights, encode_weights
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.communication.message import WeightsEnvelope
from p2pfl_tpu.learning import weights as W
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import DummyLearner, JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.parallel import ici_plane
from p2pfl_tpu.parallel.mesh import node_slices, submesh_federation_mesh
from p2pfl_tpu.settings import Settings, ici_backend
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

MLP_RULES = (
    (r"Dense_0/kernel", (None, "model")),
    (r"Dense_1/kernel", ("model", None)),
    (r"Dense_2/kernel", (None, "model")),
    (r".*", ()),
)


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    ici.ShardPlaneRegistry.reset()
    ici.reset_ici_stats()
    logger.reset_comm_metrics()
    W.reset_wire_stats()
    yield
    MemoryRegistry.reset()
    ici.ShardPlaneRegistry.reset()
    Settings.WEIGHTS_PLANE = "bytes"
    Settings.WIRE_COMPRESSION = "none"
    Settings.MEMORY_WIRE_CODEC = False


def _sum_metric(name: str) -> int:
    return int(
        sum(m.get(name, 0) for m in logger.get_comm_metrics().values())
    )


# ---------------------------------------------------------------------------
# transfer primitive (parallel/ici_plane.py)
# ---------------------------------------------------------------------------


def test_slice_info_of_shapes():
    devs = jax.devices()
    # single-device tree → synthesized one-device slice, replicated specs
    tree = {"w": jax.device_put(jnp.arange(4.0), devs[3])}
    info = ici_plane.slice_info_of(tree)
    assert info is not None and info.shape == (1,)
    assert info.device_ids == frozenset({devs[3].id})
    assert all(spec == P() for spec in info.specs)
    # placed tree → the real slice mesh + per-leaf specs
    mesh = Mesh(np.asarray(devs[:2]), ("model",))
    placed = {"w": jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P("model")))}
    pinfo = ici_plane.slice_info_of(placed)
    assert pinfo is not None and pinfo.shape == (2,)
    assert pinfo.specs == (P("model"),)
    # host leaves → not eligible
    assert ici_plane.slice_info_of({"w": np.arange(4.0)}) is None
    # leaves scattered across two single devices → not eligible
    mixed = {
        "a": jax.device_put(jnp.arange(4.0), devs[0]),
        "b": jax.device_put(jnp.arange(4.0), devs[1]),
    }
    assert ici_plane.slice_info_of(mixed) is None


def test_shard_transfer_ppermute_bit_exact_cross_slice():
    """The core primitive: a multi-leaf tree (fp32 + bf16, sharded +
    replicated leaves) moves from slice A to slice B bit-exactly and
    lands already under B's shardings."""
    devs = jax.devices()
    src_mesh = Mesh(np.asarray(devs[0:2]), ("model",))
    dst_mesh = Mesh(np.asarray(devs[2:4]), ("model",))
    specs = {"k": P("model", None), "b": P(), "h": P()}
    tree = {
        "k": jnp.arange(32.0).reshape(8, 4),
        "b": jnp.ones((5,), jnp.bfloat16) * 3,
        "h": jnp.arange(7.0),
    }
    src_tree = {
        k: jax.device_put(v, NamedSharding(src_mesh, specs[k])) for k, v in tree.items()
    }
    filler = {
        k: jax.device_put(jnp.zeros_like(v), NamedSharding(dst_mesh, specs[k]))
        for k, v in tree.items()
    }
    src = ici_plane.slice_info_of(src_tree)
    dst = ici_plane.slice_info_of(filler)
    assert ici_plane.transfer_compatible(src, dst)
    out = ici_plane.shard_transfer(src_tree, filler, src, dst, backend="ppermute")
    dst_ids = {d.id for d in dst_mesh.devices.flat}
    for key in tree:
        leaf = out[key]
        assert {d.id for d in leaf.sharding.device_set} == dst_ids
        assert leaf.sharding == NamedSharding(dst_mesh, specs[key])
        np.testing.assert_array_equal(
            np.asarray(leaf, np.float32), np.asarray(tree[key], np.float32)
        )


def test_conform_specs_counts_moved_leaves():
    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[0:2]), ("model",))
    tree = {
        "a": jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P("model"))),
        "b": jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P())),
    }
    out, moved = ici_plane.conform_specs(tree, mesh, (P(), P()))
    assert moved == 1  # only "a" changed layout
    assert out["a"].sharding == NamedSharding(mesh, P())
    assert out["b"] is tree["b"]
    np.testing.assert_array_equal(np.asarray(out["a"]), np.asarray(tree["a"]))


def test_tree_align_devices_fast_path_and_counter():
    from p2pfl_tpu.ops.tree import tree_align_copy_count, tree_align_devices

    devs = jax.devices()
    a = {"w": jax.device_put(jnp.arange(4.0), devs[0])}
    like = {"w": jax.device_put(jnp.zeros(4), devs[0])}
    before = tree_align_copy_count()
    out = tree_align_devices(a, like)
    assert out is a  # fast path: the INPUT tree comes back untouched
    assert tree_align_copy_count() == before
    # a NamedSharding over a one-device mesh of the SAME device is
    # placement-equivalent — still the fast path, still zero copies
    one = Mesh(np.asarray(devs[:1]), ("x",))
    named = {"w": jax.device_put(jnp.arange(4.0), NamedSharding(one, P()))}
    assert tree_align_devices(named, like) is named
    assert tree_align_copy_count() == before
    # genuinely elsewhere → one counted copy
    far = {"w": jax.device_put(jnp.arange(4.0), devs[1])}
    moved = tree_align_devices(far, like)
    assert tree_align_copy_count() == before + 1
    assert list(moved["w"].sharding.device_set)[0] == devs[0]


def test_ici_backend_resolver():
    prev = Settings.ICI_BACKEND
    try:
        Settings.ICI_BACKEND = "auto"
        assert ici_backend() == "ppermute"  # CPU backend in tier-1
        Settings.ICI_BACKEND = "pallas"
        assert ici_backend() == "pallas"
    finally:
        Settings.ICI_BACKEND = prev


# ---------------------------------------------------------------------------
# shard-resident codec composition (ops/compression.py entry points)
# ---------------------------------------------------------------------------


def test_shard_codec_matches_byte_codec():
    """encode_shard_device → transfer → decode_shard_device reconstructs
    the same tree as the byte codec's encode/decode for the same params
    and anchor (same math, same plan, no frame)."""
    from p2pfl_tpu.ops.compression import (
        build_topk_plan,
        decode_shard_device,
        encode_shard_device,
    )

    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=200).astype(np.float32)),
        "tiny": jnp.arange(4.0),  # under the size floor → dense int8
        "idx": jnp.arange(6, dtype=jnp.int32),  # non-float → raw
    }
    anchor = {k: v * 0.99 if v.dtype.kind == "f" else v for k, v in params.items()}
    named = dict(params)
    plan = build_topk_plan(named, anchor, 0.05)
    assert "w" in plan and "tiny" not in plan
    tk, dn, payload = encode_shard_device(named, anchor, plan, None)
    out = decode_shard_device(payload, tk, dn, anchor, named)
    # byte-path reference through the one shared decoder
    blob = W.encode_params(params, compression="topk8", anchor=anchor, anchor_tag="0:0")
    ref = W.decode_params(blob, anchor=anchor, anchor_tag="0:0")
    for key in ("w", "tiny"):
        np.testing.assert_allclose(
            np.asarray(out[key]), ref[key], atol=1e-6,
            err_msg=f"shard codec diverged from byte codec at {key}",
        )


def test_ef_residual_folds_once_across_planes():
    """Review regression: when BOTH planes encode the same update content
    (mixed fleet — ICI peers plus a byte-fallback peer cache under
    different keys), the error-feedback residual must fold exactly once;
    whichever plane encodes first owns the fold and the other goes
    residual-free instead of re-applying the just-written carry."""
    from p2pfl_tpu.learning.weights import PayloadCache

    cache = PayloadCache(owner="me")
    key = (3, 1, "topk8", "0:1")
    assert cache.ef_fold_once(key) is True    # first encoder owns the fold
    assert cache.ef_fold_once(key) is False   # later encoders go residual-free
    assert cache.ef_fold_once((3, 2, "topk8", "0:2")) is True  # new content re-arms

    # end to end: a byte encode of content the ICI plane already claimed
    # leaves the residual store untouched
    Settings.WIRE_COMPRESSION = "topk8"
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.normal(size=200).astype(np.float32))}
    anchor = {"w": params["w"] * 0.99}
    update = ModelUpdate(dict(params), ["me"], 1)
    update.anchor = anchor
    update.anchor_tag = "0:1"
    update.ef_residual = {"w": jnp.full((200,), 0.5, jnp.float32)}
    update.payload_cache = cache
    update.cache_version = 3
    update.cache_round = 1  # → fold key (3, 1, "topk8", "0:1"), claimed above
    update.encode()
    np.testing.assert_allclose(np.asarray(update.ef_residual["w"]), 0.5)

    # unclaimed content still folds normally (the carry gets rewritten)
    fresh = ModelUpdate(dict(params), ["me"], 1)
    fresh.anchor = anchor
    fresh.anchor_tag = "0:1"
    fresh.ef_residual = {"w": jnp.full((200,), 0.5, jnp.float32)}
    fresh.payload_cache = cache
    fresh.cache_version = 4
    fresh.cache_round = 1
    fresh.encode()
    assert not np.allclose(np.asarray(fresh.ef_residual["w"]), 0.5)


# ---------------------------------------------------------------------------
# federations: zero host bytes, parity, degradation, chaos
# ---------------------------------------------------------------------------


def _mlp_fleet(n, placed=False, seed_base=0):
    full = FederatedDataset.synthetic_mnist(n_train=n * 64, n_test=64, seed=0)
    slices = None
    if placed:
        gm = submesh_federation_mesh(n, model_parallel=2, devices=jax.devices()[: n * 2])
        slices = node_slices(gm)
    nodes = []
    for i in range(n):
        kw = (
            dict(mesh=slices[i], partition_rules=MLP_RULES) if placed else {}
        )
        learner = JaxLearner(
            mlp(seed=seed_base + i), full.partition(i, n), batch_size=16,
            seed=seed_base + i, **kw,
        )
        nodes.append(Node(learner=learner))
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, n - 1, only_direct=True, wait=15)
    return nodes


def _run_fleet(nodes, rounds=1, epochs=1, timeout=90):
    nodes[0].set_start_learning(rounds=rounds, epochs=epochs)
    wait_to_finish(nodes, timeout=timeout)


def _params_of(nodes):
    return [
        [np.asarray(x) for x in jax.tree.leaves(n.learner.get_parameters())]
        for n in nodes
    ]


def _stop_all(nodes):
    for n in nodes:
        n.stop()


def test_ici_federation_zero_host_bytes_and_parity():
    """The acceptance contract: a co-located federation round under
    WEIGHTS_PLANE="ici" diffuses the model with ZERO host payload bytes
    (wire/d2h counters flat, zero encode-pipeline runs), zero fallbacks,
    zero alignment fix-ups — and lands on the same parameters as the
    memory-transport baseline on the same seed."""
    nodes = _mlp_fleet(3)
    try:
        _run_fleet(nodes, rounds=2)
        baseline = _params_of(nodes)
    finally:
        _stop_all(nodes)
    MemoryRegistry.reset()
    ici.ShardPlaneRegistry.reset()

    Settings.WEIGHTS_PLANE = "ici"
    nodes = _mlp_fleet(3)
    try:
        W.reset_wire_stats()
        enc0 = W.encode_call_count()
        _run_fleet(nodes, rounds=2)
        stats = ici.ici_stats()
        wire = W.wire_stats()
        assert stats["shard_sends"] > 0
        assert stats["fallback_bytes"] == 0
        assert stats["align_violations"] == 0
        # single-chip co-resident fleet: handoffs are zero-copy, so the
        # interconnect byte counter honestly stays at zero
        assert stats["bytes_moved"] == 0
        # ZERO model-plane bytes over the host: no encode pipeline ran,
        # no payload/D2H bytes counted anywhere in the process
        assert W.encode_call_count() == enc0
        assert wire["payload_bytes"] == 0 and wire["d2h_bytes"] == 0
        assert _sum_metric("ici_send_shard") == stats["shard_sends"]
        # receiver-side alignment stayed the no-op the plane asserts
        assert _sum_metric("tree_align_copies") == 0
        # within the ICI run, the fleet converges on one model — the
        # strong per-run statement, immune to cross-run gossip timing
        params = _params_of(nodes)
        for other in params[1:]:
            for x, y in zip(params[0], other):
                np.testing.assert_allclose(x, y, atol=1e-5)
        # bit-close to the memory-transport baseline: gossip fold order
        # is arrival-order dependent, so two runs of the SAME transport
        # already differ by summation-order noise — this tolerance is
        # that cross-run floor, far under any codec/transport error
        for a, b in zip(baseline, params):
            for x, y in zip(a, b):
                np.testing.assert_allclose(x, y, atol=1e-3)
    finally:
        _stop_all(nodes)


def test_ici_cross_slice_placed_federation():
    """Submesh-placed learners on DISJOINT 2-device slices: the weights
    plane moves real shards via the ppermute pair program — zero host
    bytes, zero fallbacks, parameters matching the bytes baseline."""
    nodes = _mlp_fleet(2, placed=True)
    try:
        _run_fleet(nodes, rounds=2)
        baseline = _params_of(nodes)
    finally:
        _stop_all(nodes)
    MemoryRegistry.reset()
    ici.ShardPlaneRegistry.reset()

    Settings.WEIGHTS_PLANE = "ici"
    nodes = _mlp_fleet(2, placed=True)
    try:
        W.reset_wire_stats()
        _run_fleet(nodes, rounds=2)
        stats = ici.ici_stats()
        wire = W.wire_stats()
        assert stats["shard_sends"] > 0 and stats["fallback_bytes"] == 0
        assert stats["align_violations"] == 0
        # disjoint slices: real shards crossed the (virtual) interconnect
        assert stats["bytes_moved"] > 0
        assert wire["payload_bytes"] == 0 and wire["d2h_bytes"] == 0
        for a, b in zip(baseline, _params_of(nodes)):
            for x, y in zip(a, b):
                np.testing.assert_allclose(x, y, atol=1e-3)
    finally:
        _stop_all(nodes)


def test_ici_topk8_codec_end_to_end_on_device():
    """WIRE_COMPRESSION="topk8" composes with the plane: the device
    codec's buffers move shard-to-shard and reconstruct against the
    receiver's anchor — still zero host payload bytes, and bit-close to
    the BYTE-path (MEMORY_WIRE_CODEC) baseline running the same codec."""
    Settings.WIRE_COMPRESSION = "topk8"
    Settings.MEMORY_WIRE_CODEC = True
    nodes = _mlp_fleet(2)
    try:
        _run_fleet(nodes, rounds=2)
        baseline = _params_of(nodes)
    finally:
        _stop_all(nodes)
    MemoryRegistry.reset()
    ici.ShardPlaneRegistry.reset()

    Settings.MEMORY_WIRE_CODEC = False
    Settings.WEIGHTS_PLANE = "ici"
    nodes = _mlp_fleet(2)
    try:
        W.reset_wire_stats()
        _run_fleet(nodes, rounds=2)
        stats = ici.ici_stats()
        wire = W.wire_stats()
        assert stats["shard_sends"] > 0
        assert stats["align_violations"] == 0
        assert wire["payload_bytes"] == 0 and wire["d2h_bytes"] == 0
        # the codec is lossy (topk8), so parity is codec-tolerance close,
        # not bit-equal — the same budget the byte path grants itself
        for a, b in zip(baseline, _params_of(nodes)):
            for x, y in zip(a, b):
                np.testing.assert_allclose(x, y, atol=5e-2)
    finally:
        _stop_all(nodes)


def test_ici_mixed_fleet_falls_back_per_peer():
    """Transport selection + degradation (ISSUE 13 satellite): a 3-node
    fleet where one peer is NOT on the shard plane must complete the
    round with per-peer byte fallback — loudly counted, never aborted —
    and the fallback frames must carry the "sp" handshake header through
    the real byte path."""
    Settings.WEIGHTS_PLANE = "ici"
    Settings.MEMORY_WIRE_CODEC = True  # fallback = the REAL byte path
    nodes = _mlp_fleet(3)
    outsider = nodes[-1]
    # the outsider never joined the shard plane (models another process /
    # another fabric) — its edges must ride bytes in both directions
    ici.ShardPlaneRegistry.unregister(outsider.addr)
    seen_sp = []
    orig_handle = outsider.protocol.handle_weights

    def spy_handle(env):
        seen_sp.append(env.update.sp)
        return orig_handle(env)

    outsider.protocol.handle_weights = spy_handle
    try:
        _run_fleet(nodes, rounds=1)
        stats = ici.ici_stats()
        assert stats["shard_sends"] > 0, "co-located pair stopped using the plane"
        assert stats["fallback_bytes"] > 0, "outsider edges never fell back"
        assert _sum_metric("ici_fallback_bytes") == stats["fallback_bytes"]
        # every node finished the round — degradation, not abort
        for n in nodes:
            assert n.state.round is None
        # the byte-path frames advertised the sender's slice topology
        # (and the memory byte path copied the optional header through)
        assert any(sp is not None and tuple(sp[0]) == (1,) for sp in seen_sp)
        # params converged across ALL nodes, outsider included
        params = _params_of(nodes)
        for a, b in zip(params[0], params[-1]):
            np.testing.assert_allclose(a, b, atol=1e-3)
    finally:
        _stop_all(nodes)


def test_ici_chaos_drop_slow_crash_federation():
    """The chaos suite composes with the plane: 6 nodes under 5% drop,
    a slow peer and a mid-round crash, weights riding ICI. Survivors
    finish every round via train-set repair, fault verdicts land on ICI
    edges (the injector wraps the plane at the _do_send seam), and the
    corpse's edges fail like any dead peer's."""
    Settings.WEIGHTS_PLANE = "ici"
    Settings.TRAIN_SET_SIZE = 6
    Settings.AGGREGATION_TIMEOUT = 60.0
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(6)]
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, 5, only_direct=True, wait=10)
    victim, slow = nodes[3], nodes[-1]
    plan = FaultPlan(
        seed=1905,
        default=EdgeFault(drop=0.05),
        slow_nodes={slow.addr: 0.2},
        crashes={victim.addr: CrashSpec(stage="TrainStage", round_no=0)},
    )
    install_fault_plan(nodes, plan)
    survivors = [n for n in nodes if n is not victim]
    try:
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(survivors, timeout=45)
        assert time.monotonic() - t0 < 45.0
        assert not victim._running
        stats = ici.ici_stats()
        assert stats["shard_sends"] > 0, "chaos federation never used the plane"
        # the injector saw the ICI sends: drop verdicts were exercised on
        # weights-plane envelopes too (scope="both" default)
        assert _sum_metric("fault_drop") > 0
        assert _sum_metric("train_set_repair") >= 1
        for n in survivors:
            assert n.state.round is None
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        remove_fault_plan(nodes)
        _stop_all(nodes)


def test_ici_dead_peer_fails_send_like_bytes():
    """A crashed peer's ICI sends must FAIL (feeding breakers/eviction),
    not fall back or hang — same signals as the byte path."""
    Settings.WEIGHTS_PLANE = "ici"
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(2)]
    for n in nodes:
        n.start()
    full_connection(nodes[0], nodes)
    wait_convergence(nodes, 1, only_direct=True, wait=10)
    try:
        from p2pfl_tpu.communication.faults import hard_crash

        hard_crash(nodes[1])
        update = nodes[0].learner.get_model_update()
        env = nodes[0].protocol.build_weights("add_model", 0, update)
        assert nodes[0].protocol._send_to_neighbor(nodes[1].addr, env) is False
        assert ici.ici_stats()["shard_sends"] == 0
    finally:
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# the "sp" wire header (handshake satellite)
# ---------------------------------------------------------------------------


def test_sp_header_codec_roundtrip_and_absent_frame():
    update = ModelUpdate(
        params=None, contributors=["a"], num_samples=3, encoded=b"\x00payload",
        sp=((2, 2), 1, "topk8"),
    )
    env = WeightsEnvelope("src", 4, "add_model", update)
    out = decode_weights(encode_weights(env))
    assert out.update.sp == ((2, 2), 1, "topk8")
    # absent frame (old sender) decodes unchanged — no key, None field
    old = ModelUpdate(params=None, contributors=["a"], num_samples=3, encoded=b"\x00p")
    out2 = decode_weights(encode_weights(WeightsEnvelope("src", 1, "add_model", old)))
    assert out2.update.sp is None


def test_sp_header_never_in_protobuf_interop():
    import ast as _ast
    import inspect

    from p2pfl_tpu.communication import proto_wire

    tree = _ast.parse(inspect.getsource(proto_wire))
    for node in _ast.walk(tree):
        assert not (isinstance(node, _ast.Constant) and node.value == "sp")
