"""SPMD federation tests on the 8-device virtual CPU mesh (SURVEY §4 note:
``xla_force_host_platform_device_count`` replaces "multi-node without a
cluster")."""

import jax
import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import SpmdFederation, federation_mesh
from p2pfl_tpu.parallel.spmd import spmd_round  # noqa: F401


def _dataset(n_train=2048, n_test=512):
    return FederatedDataset.synthetic_mnist(n_train=n_train, n_test=n_test)


def test_mesh_shapes():
    mesh = federation_mesh()
    assert mesh.devices.size == len(jax.devices())
    # fewer slots than devices needs an explicit device subset — bare
    # n_nodes used to silently strand the trailing devices (ISSUE 10
    # satellite: the node-folding edge case now raises, pinned in
    # tests/test_submesh.py)
    mesh2 = federation_mesh(n_nodes=4, devices=jax.devices()[:4])
    assert mesh2.shape["nodes"] == 4


@pytest.mark.slow
def test_spmd_federation_learns():
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=8, batch_size=64, vote=False
    )
    before = fed.evaluate()["test_acc"]
    fed.run(rounds=3, epochs=1)
    after = fed.evaluate()["test_acc"]
    assert after > before
    assert after > 0.9  # synthetic task is easy


@pytest.mark.slow
def test_spmd_nodes_all_equal_after_round():
    """Diffusion: after a round every node holds the same aggregated model."""
    fed = SpmdFederation.from_dataset(mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False)
    fed.run_round()
    p0 = jax.tree.leaves(fed.node_params(0))
    p3 = jax.tree.leaves(fed.node_params(3))
    for a, b in zip(p0, p3):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-6)


def test_spmd_vote_masks_nodes():
    """With TRAIN_SET_SIZE < N, only elected nodes contribute."""
    from p2pfl_tpu.settings import Settings

    Settings.TRAIN_SET_SIZE = 2
    fed = SpmdFederation.from_dataset(mlp(), _dataset(), n_nodes=4, batch_size=64, vote=True)
    fed.run_round()
    assert int(fed.train_mask.sum()) == 2


def test_spmd_keep_opt_state():
    """Optimizer-moment carry-over across rounds (improvement knob) runs."""
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False, keep_opt_state=True
    )
    fed.run(rounds=2)
    assert fed.round == 2 and fed.evaluate()["test_acc"] > 0.9


def test_spmd_nondivisible_node_count():
    """5 nodes on 8 devices: folds onto a smaller mesh, still works."""
    fed = SpmdFederation.from_dataset(mlp(), _dataset(), n_nodes=5, batch_size=32, vote=False)
    fed.run_round()
    assert fed.round == 1


@pytest.mark.parametrize("agg", ["median", "trimmed_mean", "krum"])
def test_spmd_robust_aggregators_resist_byzantine(agg):
    """A poisoned node (garbage weights) must not destroy the aggregate."""
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False, aggregator=agg, trim=1
    )
    # poison node 0's params with huge noise
    poisoned = jax.tree.map(
        lambda x: x.at[0].set(jax.random.normal(jax.random.PRNGKey(0), x.shape[1:]) * 100.0),
        fed.params,
    )
    fed.params = poisoned
    fed.run_round()
    acc = fed.evaluate()["test_acc"]
    assert acc > 0.5  # fedavg would collapse to ~0.1 here


@pytest.mark.slow
def test_spmd_robust_agg_with_partial_mask_trains():
    """Regression (ADVICE r1 high): with TRAIN_SET_SIZE < N, robust
    aggregators must see elected rows only — stale non-elected copies
    would otherwise dominate the coordinate-wise median and freeze training."""
    from p2pfl_tpu.settings import Settings

    Settings.TRAIN_SET_SIZE = 4
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=8, batch_size=64, vote=True, aggregator="median"
    )
    before = [np.asarray(x, np.float32) for x in jax.tree.leaves(fed.node_params(0))]
    fed.run(rounds=3)
    after = [np.asarray(x, np.float32) for x in jax.tree.leaves(fed.node_params(0))]
    delta = max(float(np.max(np.abs(a - b))) for a, b in zip(before, after))
    assert delta > 0.0, "aggregate never moved — robust agg saw stale slots"
    assert fed.evaluate()["test_acc"] > 0.5


def test_spmd_trimmed_mean_trim_clamped():
    """Regression (ADVICE r1): 2*trim >= K must clamp, not produce NaN params."""
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False,
        aggregator="trimmed_mean", trim=3,
    )
    fed.run_round()
    assert all(np.isfinite(np.asarray(x, np.float32)).all() for x in jax.tree.leaves(fed.params))


@pytest.mark.slow
def test_spmd_unequal_shards_sample_weighting():
    """Regression (ADVICE r1): unequal shards shuffle over their OWN sample
    range (not the truncated min), so FedAvg's sample-count weights match the
    data each node actually trains on."""
    data = _dataset()
    shards = [data.partition(i, 4, strategy="dirichlet", alpha=0.3) for i in range(4)]
    sizes = [s.num_samples for s in shards]
    assert len(set(sizes)) > 1, "dirichlet partition should produce unequal shards"
    fed = SpmdFederation(mlp(), shards, batch_size=16, vote=False)
    assert fed._tr_size == max(sizes)
    perm = np.asarray(jax.device_get(fed._make_perm(epochs=1)))
    for i, size in enumerate(sizes):
        assert perm[i].max() < size  # indices stay inside the node's own shard
    fed.run(rounds=2)
    assert fed.evaluate()["test_acc"] > 0.5


@pytest.mark.slow
def test_spmd_matches_node_mode_fedavg():
    """SPMD round == Node-mode round semantics: FedAvg of locally-trained models.

    Both paths start from identical params and see identical data; with
    epochs=0-style no-op training removed, we instead verify the aggregate
    equals the hand-computed weighted mean of per-node trained params.
    """
    from p2pfl_tpu.learning.learner import adam
    from p2pfl_tpu.ops.tree import tree_stack, tree_weighted_mean

    model = mlp()
    data = _dataset(n_train=1024)
    shards = [data.partition(i, 2) for i in range(2)]
    fed = SpmdFederation(model, shards, batch_size=64, vote=False, seed=7)

    # replay: train each node independently with the same shuffles
    rng = np.random.default_rng(7)
    perms = [
        rng.permutation(fed._tr_size)[: fed._nb * fed.batch_size].reshape(fed._nb, fed.batch_size)
        for _ in range(2)
    ]
    import jax.numpy as jnp

    from p2pfl_tpu.parallel.spmd import _local_epoch

    tx = adam(1e-3)
    manual = []
    for i, shard in enumerate(shards):
        p = model.params
        o = tx.init(p)
        xs = jnp.asarray(shard.x_train[: fed._tr_size][perms[i]])
        ys = jnp.asarray(shard.y_train[: fed._tr_size][perms[i]])
        p, o, _ = _local_epoch(p, o, xs, ys, model.module, tx)
        manual.append(p)
    expected = tree_weighted_mean(manual, [s.num_samples for s in shards])

    fed.run_round()
    got = fed.node_params(0)
    for a, b in zip(jax.tree.leaves(expected), jax.tree.leaves(got)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=2e-2
        )


@pytest.mark.slow
def test_run_fused_matches_sequential_rounds():
    """R fused rounds (one dispatch) == R sequential run_round calls with
    the same RNG seed — identical math, amortized dispatch."""
    fa = SpmdFederation.from_dataset(mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False, seed=3)
    fb = SpmdFederation.from_dataset(mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False, seed=3)
    for _ in range(3):
        fa.run_round(epochs=1)
    entries = fb.run_fused(3, epochs=1, eval=True)
    assert fb.round == 3 and len(entries) == 3
    assert float(entries[-1]["test_acc"]) > 0.5
    for a, b in zip(jax.tree.leaves(fa.params), jax.tree.leaves(fb.params)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=2e-5, rtol=1e-4
        )


@pytest.mark.slow
def test_run_fused_composes_with_scaffold_and_fedopt():
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False,
        scaffold=True, optimizer="sgd", learning_rate=0.05,
        server_opt="adam", server_lr=0.01,
    )
    entries = fed.run_fused(3, epochs=1, eval=True)
    assert fed._server_t == 3
    assert float(entries[-1]["test_acc"]) > float(entries[0]["test_acc"]) or (
        float(entries[0]["test_acc"]) > 0.9
    )


def test_run_fused_rejects_per_round_election():
    from p2pfl_tpu.settings import Settings

    fed = SpmdFederation.from_dataset(mlp(), _dataset(), n_nodes=4, batch_size=64, vote=True)
    Settings.VOTE_EVERY_ROUND = True
    try:
        with pytest.raises(ValueError, match="fixed mask"):
            fed.run_fused(2)
    finally:
        Settings.VOTE_EVERY_ROUND = False


@pytest.mark.slow
def test_spmd_bulyan_survives_byzantine_noise():
    """Bulyan in the jitted round (iterated Krum + trimmed mean): 8 nodes,
    1 Byzantine slot overwritten with large noise each round — training
    still converges. K=8 satisfies N >= 4f+3 for f=1."""
    fed = SpmdFederation.from_dataset(
        mlp(), _dataset(), n_nodes=8, batch_size=64, vote=False,
        aggregator="bulyan", trim=1,
    )
    key = jax.random.PRNGKey(0)
    for _ in range(3):
        key, sub = jax.random.split(key)  # fresh garbage every round
        fed.params = jax.tree.map(
            lambda x, sub=sub: x.at[:1].set(jax.random.normal(sub, x.shape[1:], x.dtype) * 10.0),
            fed.params,
        )
        fed.run_round(epochs=1)
    assert fed.evaluate()["test_acc"] > 0.8

    with pytest.raises(ValueError, match="4f"):
        bad = SpmdFederation.from_dataset(
            mlp(), _dataset(), n_nodes=4, batch_size=64, vote=False,
            aggregator="bulyan", trim=1,
        )
        bad.run_round()


def test_spmd_deterministic_across_runs():
    """Same seed, same data → bit-identical federations after 2 rounds.

    Reproducibility is a real capability claim: per-round shuffles come
    from the host rng (seeded), initialization from the model seed, and
    XLA executes deterministically on a fixed device set.
    """
    data = _dataset(n_train=512, n_test=128)

    def run():
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=4, batch_size=64, vote=True, seed=11
        )
        fed.run(rounds=2, epochs=1)
        return [np.asarray(x) for x in jax.tree.leaves(fed.params)]

    a, b = run(), run()
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
