"""End-to-end learning tests (reference ``test/node_test.py`` scenarios):

convergence to equal models, interrupt mid-learning, node death mid-learning,
architecture mismatch must not hang the network — all with real Node objects
over the in-memory transport in one process (SURVEY §4).
"""

import time

import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import DummyLearner, JaxLearner
from p2pfl_tpu.models import cnn, mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import (
    check_equal_models,
    connect_line,
    full_connection,
    wait_convergence,
    wait_to_finish,
)


@pytest.fixture(autouse=True)
def _clean_registry():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


def _data(i, n, n_train=512, n_test=128):
    full = FederatedDataset.synthetic_mnist(n_train=n_train, n_test=n_test)
    return full.partition(i, n)


def _mk_ml_nodes(n, model_fn=mlp, epochs_data=None):
    nodes = []
    for i in range(n):
        model = model_fn(seed=i)
        learner = JaxLearner(model, _data(i, n), batch_size=64)
        nodes.append(Node(learner=learner))
    for node in nodes:
        node.start()
    return nodes


def _stop_all(nodes):
    for n in nodes:
        n.stop()


@pytest.mark.parametrize("rounds", [1, 2])
def test_convergence_two_nodes(rounds):
    """Reference ``test_node_test.py:74-100`` — its CI anchor scenario."""
    nodes = _mk_ml_nodes(2)
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=rounds, epochs=0)
    wait_to_finish(nodes, timeout=60)
    check_equal_models(nodes)
    _stop_all(nodes)


@pytest.mark.slow
def test_convergence_four_nodes_line_with_training():
    """4 nodes on a line topology, one epoch of real training each round."""
    nodes = _mk_ml_nodes(4)
    connect_line(nodes)
    wait_convergence(nodes, 3, only_direct=False)
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes)
    _stop_all(nodes)


def test_eight_node_training_improves_accuracy_memory():
    """8-node gossip federation, epochs=1: accuracy must actually improve,
    not just end equal (VERDICT r1 #10) — over the in-memory transport."""
    nodes = []
    for i in range(8):
        learner = JaxLearner(mlp(seed=i), _data(i, 8, n_train=4096, n_test=1024), batch_size=64)
        node = Node(learner=learner)
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 7, only_direct=True)
    before = nodes[0].learner.evaluate()["test_acc"]
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=240)
    check_equal_models(nodes)
    after = nodes[0].learner.evaluate()["test_acc"]
    assert after > before and after > 0.85, (before, after)
    _stop_all(nodes)


@pytest.mark.slow
def test_eight_node_training_improves_accuracy_grpc():
    """Same as above over real gRPC sockets (wire-encoded weights)."""
    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol

    nodes = []
    for i in range(8):
        learner = JaxLearner(mlp(seed=i), _data(i, 8, n_train=4096, n_test=1024), batch_size=64)
        node = Node(learner=learner, protocol=GrpcProtocol("127.0.0.1:0"))
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 7, only_direct=True)
    before = nodes[0].learner.evaluate()["test_acc"]
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=240)
    check_equal_models(nodes)
    after = nodes[0].learner.evaluate()["test_acc"]
    assert after > before and after > 0.85, (before, after)
    _stop_all(nodes)


def test_dummy_learner_federation():
    """FSM correctness without ML: dummy learners converge to one value."""
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(3)]
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=30)
    check_equal_models(nodes, atol=1e-6)
    _stop_all(nodes)


def test_interrupt_learning():
    nodes = _mk_ml_nodes(2)
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=10, epochs=1)
    time.sleep(0.5)
    nodes[0].set_stop_learning()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if all(n.state.round is None for n in nodes):
            break
        time.sleep(0.1)
    assert all(n.state.round is None for n in nodes)
    _stop_all(nodes)


def test_node_down_on_learning():
    """Kill a node mid-learning; the rest must still finish (reference :126-152)."""
    nodes = _mk_ml_nodes(4)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 3, only_direct=True)
    nodes[0].set_start_learning(rounds=2, epochs=1)
    time.sleep(1)
    nodes[-1].stop()
    wait_to_finish(nodes[:-1], timeout=120)
    _stop_all(nodes[:-1])


@pytest.mark.slow
def test_wrong_model_does_not_hang():
    """MLP vs CNN (reference :155-176): mismatched node stops, net finishes."""
    Settings.VOTE_TIMEOUT = 3.0
    Settings.AGGREGATION_TIMEOUT = 3.0
    n1 = Node(learner=JaxLearner(mlp(seed=0), _data(0, 2), batch_size=64))
    n2 = Node(learner=JaxLearner(cnn(seed=1), _data(1, 2), batch_size=64))
    n1.start()
    n2.start()
    n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)
    n1.set_start_learning(rounds=1, epochs=0)
    wait_to_finish([n1], timeout=60)
    _stop_all([n1, n2])


def test_stale_round_add_model_rejected():
    """A previous round's diffused aggregate must not satisfy the CURRENT
    round's collection window (the train set is reused across rounds, so
    its contributor set matches exactly — without a round gate the window
    accepts it and the round's training is silently discarded)."""
    from p2pfl_tpu.learning.weights import ModelUpdate

    learner = JaxLearner(mlp(), _data(0, 2), batch_size=64)
    node = Node(learner=learner)
    node.start()
    try:
        node.state.model_initialized_event.set()
        node.state.round = 2
        node.state.train_set = [node.addr, "peer"]
        node.aggregator.set_nodes_to_aggregate([node.addr, "peer"])
        stale = ModelUpdate(learner.get_parameters(), [node.addr, "peer"], 10)
        # round 1 payload into a round-2 window: rejected by the gate
        from p2pfl_tpu.commands.learning import AddModelCommand

        AddModelCommand(node).execute("peer", 1, update=stale)
        assert node.aggregator.get_aggregated_models() == []
        # same payload at the CURRENT round is accepted
        AddModelCommand(node).execute("peer", 2, update=stale)
        assert node.aggregator.get_aggregated_models() == sorted([node.addr, "peer"])
    finally:
        node.stop()


def test_future_round_individual_rejected():
    """ADVICE r2 (low): a fast peer one round ahead gossips its round-r+1
    INDIVIDUAL model; folding it into the round-r window would mix two
    rounds' models. Only a full-coverage future aggregate (the catch-up
    case) may pass."""
    from p2pfl_tpu.commands.learning import AddModelCommand
    from p2pfl_tpu.learning.weights import ModelUpdate

    learner = JaxLearner(mlp(), _data(0, 2), batch_size=64)
    node = Node(learner=learner)
    node.start()
    try:
        node.state.model_initialized_event.set()
        node.state.round = 1
        node.state.train_set = [node.addr, "peer"]
        node.aggregator.set_nodes_to_aggregate([node.addr, "peer"])
        cmd = AddModelCommand(node)

        # future-round INDIVIDUAL contribution: rejected by the gate
        indiv = ModelUpdate(learner.get_parameters(), ["peer"], 10)
        cmd.execute("peer", 2, update=indiv)
        assert node.aggregator.get_aggregated_models() == []

        # future-round FULL aggregate: the liveness/catch-up case, accepted
        full = ModelUpdate(learner.get_parameters(), [node.addr, "peer"], 10)
        cmd.execute("peer", 2, update=full)
        assert node.aggregator.get_aggregated_models() == sorted([node.addr, "peer"])
    finally:
        node.stop()
