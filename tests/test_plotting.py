"""Metric-curve rendering (``management/plotting.py``) — parity with the
reference example's matplotlib output (``p2pfl/examples/mnist.py:124-157``),
rendered to PNG on this headless rig."""

import os

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.plotting import (
    plot_global_metrics,
    plot_history,
    plot_local_metrics,
)


def test_plot_global_and_local_from_logger(tmp_path):
    logger.register_node("plot-node")
    try:
        for rnd in (0, 1, 2):
            logger.log_metric(
                "plot-node", "test_acc", 0.5 + 0.1 * rnd, round=rnd, experiment="plot-exp"
            )
            for step in range(4):
                logger.log_metric(
                    "plot-node", "train_loss", 2.0 - 0.1 * step, step=step,
                    round=rnd, experiment="plot-exp",
                )
        g = plot_global_metrics(str(tmp_path / "g.png"), experiment="plot-exp")
        l = plot_local_metrics(str(tmp_path / "l.png"), experiment="plot-exp")
        assert g and os.path.getsize(g) > 1000
        assert l and os.path.getsize(l) > 1000
    finally:
        logger.unregister_node("plot-node")


def test_plot_global_empty_returns_none(tmp_path):
    assert plot_global_metrics(str(tmp_path / "x.png"), experiment="no-such-exp") is None


def test_plot_history(tmp_path):
    hist = [
        {"round": r, "train_loss": 2.0 / (r + 1), "test_acc": 0.3 + 0.2 * r}
        for r in range(4)
    ]
    p = plot_history(hist, str(tmp_path / "h.png"), title="t")
    assert p and os.path.getsize(p) > 1000
    assert plot_history([], str(tmp_path / "e.png")) is None


def test_plot_history_late_appearing_metric(tmp_path):
    """Metric keys are unioned across ALL entries (ADVICE: plotting.py
    derived them from history[0] only) — a metric first logged in round 2
    still gets a curve, entries missing it are just skipped points."""
    hist = [
        {"round": 0, "train_loss": 2.0},
        {"round": 1, "train_loss": 1.5},
        {"round": 2, "train_loss": 1.2, "test_acc": 0.41},
        {"round": 3, "train_loss": 1.0, "test_acc": 0.55},
    ]
    p = plot_history(hist, str(tmp_path / "late.png"), title="late")
    assert p and os.path.getsize(p) > 1000
