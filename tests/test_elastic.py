"""Elastic fleet (ISSUE 11): one routing core, churn, root failover.

Five layers, mirroring the change's structure:

- the node-free :class:`TierRouter` (the shared core BOTH drivers — the
  production ``AsyncContext`` and ``SimulatedAsyncFleet`` — consume):
  decision matrix, permutation invariance, the bounded-disruption
  contract of a removal, successor election;
- buffer migration primitives: ``take_pending`` forwarding and the
  version high-water jump that keeps minting monotone across a root
  handover;
- the experiment-identity "xp" wire header: codec round-trip, old-frame
  compat, and the exact stash filters it replaces heuristics with;
- the simulator under a full churn plan (joins + graceful/abrupt leaves
  + a global-root kill): bit-exact replay, 1k-node re-convergence with
  bounded disruption, and the kill-the-root-mid-flush version-monotonicity
  regression;
- real nodes over the in-memory transport: root kill with self-elected
  successor, a mid-experiment join bootstrapping from the fleet's
  global, and a graceful leave that loses nothing.
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    CrashSpec,
    EdgeFault,
    FaultPlan,
    JoinSpec,
    LeaveSpec,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.grpc_transport import (
    decode_message,
    decode_weights,
    encode_message,
    encode_weights,
)
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.federation import (
    BufferedAggregator,
    SimulatedAsyncFleet,
    TierRouter,
    VersionHighWater,
)
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    yield
    Settings.FEDERATION_MODE = "sync"
    Settings.HIER_CLUSTER_SIZE = 0
    MemoryRegistry.reset()


# ---------------------------------------------------------------------------
# TierRouter: the shared routing core (exercised once for both drivers)
# ---------------------------------------------------------------------------


def test_router_decision_matrix():
    """The full decision surface on a 7-member, cluster-3 fleet:
    clusters [a,b,c] + [d,e,f,g] (trailing singleton folded), root=a."""
    members = list("abcdefg")
    r = TierRouter(members, 3)
    assert r.topo.clusters == [["a", "b", "c"], ["d", "e", "f", "g"]]
    assert r.root == "a" and r.regionals == ["a", "d"]
    assert r.roles() == {
        "a": "global", "b": "edge", "c": "edge",
        "d": "regional", "e": "edge", "f": "edge", "g": "edge",
    }
    # push targets: own cluster's regional (self-offers for aggregators)
    assert r.push_target("b") == "a" and r.push_target("e") == "d"
    assert r.push_target("a") == "a" and r.push_target("d") == "d"
    # update sinks: peer-regional aggregates feed the root's global
    # buffer, own-cluster (and orphaned) updates its regional buffer
    assert r.update_sink("a", "d") == "global"
    assert r.update_sink("a", "b") == "regional"
    assert r.update_sink("a", "a") == "regional"
    assert r.update_sink("a", "f") == "regional"  # orphan absorption
    assert r.update_sink("d", "e") == "regional"
    assert r.update_sink("b", "a") is None  # edges hold no buffer
    # push-down fan-outs
    assert r.live_children("a") == ["d", "b", "c"]
    assert r.live_children("d") == ["e", "f", "g"]
    assert r.live_children("b") == []
    # buffer plans (K clamped to live fan-in)
    assert r.buffer_plan("a", 4) == (3, 2)
    assert r.buffer_plan("d", 4) == (4, None)
    assert r.buffer_plan("b", 4) == (None, None)
    # flat collapse: one global buffer at the root, K clamped to the fleet
    flat = TierRouter(members, 0)
    assert flat.buffer_plan("a", 4) == (None, 4)
    assert flat.update_sink("a", "g") == "global"
    assert flat.live_children("a") == ["b", "c", "d", "e", "f", "g"]


def test_router_permutation_invariance():
    """Any permutation of the same live membership yields identical
    tiers/roles — what lets every node derive the topology alone."""
    import random as _random

    members = [f"n{i:03d}" for i in range(23)]
    base = TierRouter(members, 5, dead={"n004", "n010"})
    for seed in range(5):
        shuffled = list(members)
        _random.Random(seed).shuffle(shuffled)
        r = TierRouter(shuffled, 5, dead={"n010", "n004"})
        assert r.roles() == base.roles()
        assert r.topo.clusters == base.topo.clusters
        assert r.root == base.root and r.regionals == base.regionals


def test_router_removal_bounded_disruption():
    """The bounded-disruption contract: removing ONE member changes role
    assignments only within the affected cluster (successor election)
    plus the root chain — every other cluster's roles are untouched."""
    members = [f"n{i:03d}" for i in range(40)]
    base = TierRouter(members, 8)
    base_roles = base.roles()
    for victim in members:
        r = TierRouter(members, 8, dead={victim})
        new_roles = r.roles()
        vi = base.topo.cluster_index(victim)
        assert new_roles[victim] == "dead"
        for m in members:
            if m == victim or base.topo.cluster_index(m) == vi:
                continue  # the affected cluster may re-elect
            assert new_roles[m] == base_roles[m], (victim, m)
        # clusters themselves never re-chunk on a death (holes, not
        # re-derivation from the shrunk list)
        assert r.topo.clusters == base.topo.clusters


def test_router_successor_election():
    """A dead regional's cluster re-elects its next live member; a dead
    root hands the fleet to the next-sorted live regional; K clamps
    follow the live fan-in (the eviction-repair contract)."""
    members = list("abcdefgh")  # clusters [a,b,c,d], [e,f,g,h] at size 4
    base = TierRouter(members, 4)
    assert base.root == "a" and base.regionals == ["a", "e"]
    # regional e dies: f self-elects, root unchanged
    r = TierRouter(members, 4, dead={"e"})
    assert r.role("f") == "regional" and r.root == "a"
    assert r.push_target("g") == "f"
    assert r.buffer_plan("f", 4) == (3, None)
    # the ROOT dies: its cluster re-elects b, which is also the
    # next-sorted live regional — so b is the successor root
    r = TierRouter(members, 4, dead={"a"})
    assert r.role("b") == "global" and r.root == "b"
    assert r.regionals == ["b", "e"]
    assert r.push_target("c") == "b"
    # the whole first cluster dies: the fleet re-roots on e's cluster
    r = TierRouter(members, 4, dead={"a", "b", "c", "d"})
    assert r.root == "e" and r.regionals == ["e"]
    # a fully dead cluster's push target falls back to the root
    assert r.push_target("b") == "e"


# ---------------------------------------------------------------------------
# buffer migration primitives
# ---------------------------------------------------------------------------


def _update(value, contributors, num_samples=1, version=None, dim=4):
    upd = ModelUpdate({"w": np.full(dim, value, np.float32)}, list(contributors), num_samples)
    upd.version = version
    return upd


def test_version_high_water():
    hw = VersionHighWater()
    hw.observe(3)
    hw.observe(None)
    hw.observe(1)
    assert hw.mark == 3
    hw.observe(7)
    assert hw.mark == 7


def test_buffer_high_water_jump_keeps_minting_monotone():
    """A successor root seeded below the fleet's real version must mint
    ABOVE any base_version it observes — the mid-flush-kill contract."""
    buf = BufferedAggregator("succ", {"w": np.zeros(4, np.float32)}, k=2, alpha=0.0)
    assert buf.version == 0
    # an update trained from v5 (minted by the dead root) arrives
    buf.offer(_update(1.0, ["a"], version=("a", 1, 5)))
    assert buf.version == 5, "counter did not jump to the observed base"
    res = buf.offer(_update(2.0, ["b"], version=("b", 1, 5)))
    assert res is not None and res.version == 6, "mint regressed below the high water"
    # regional tiers never jump: their counter tracks the global push
    rbuf = BufferedAggregator(
        "reg", {"w": np.zeros(4, np.float32)}, k=2, alpha=0.0, bump_on_flush=False
    )
    rbuf.offer(_update(1.0, ["a"], version=("a", 1, 5)))
    assert rbuf.version == 0


def test_buffer_take_pending_preserves_dedup():
    """Demotion migration: take_pending drains the partial buffer in
    (origin, seq) order without merging, and the vector still rejects a
    replay of what was accepted (re-promotion safety)."""
    buf = BufferedAggregator("me", {"w": np.zeros(4, np.float32)}, k=3, alpha=0.0)
    buf.offer(_update(2.0, ["b"], version=("b", 1, 0)))
    buf.offer(_update(1.0, ["a"], version=("a", 1, 0)))
    pending = buf.take_pending()
    assert [u.version[0] for u in pending] == ["a", "b"]
    assert buf.pending() == 0
    assert buf.offer(_update(1.0, ["a"], version=("a", 1, 0))) is None
    assert logger.get_comm_metrics("me").get("async_dup_drop", 0) == 1


# ---------------------------------------------------------------------------
# the "xp" experiment-identity wire header
# ---------------------------------------------------------------------------


def test_wire_xp_roundtrip_and_old_frame_compat():
    msg = Message("a", "async_done", (), 0, xp="xid-1")
    out = decode_message(encode_message(msg))
    assert out.xp == "xid-1"
    # absent on old senders: the key never appears, decode yields None
    raw = encode_message(Message("a", "beat", ("1",), 0))
    assert b'"xp"' not in raw
    assert decode_message(raw).xp is None

    upd = ModelUpdate({"w": np.ones(3, np.float32)}, ["a"], 2)
    upd.xp = "xid-2"
    env = WeightsEnvelope("a", 0, "async_update", upd)
    out = decode_weights(encode_weights(env))
    assert out.xp == "xid-2" and out.update.xp == "xid-2"
    clean = WeightsEnvelope("a", 0, "add_model", ModelUpdate({"w": np.ones(3, np.float32)}, ["a"], 2))
    raw = encode_weights(clean)
    assert b'"xp"' not in raw
    assert decode_weights(raw).update.xp is None


def test_async_stash_filters_on_experiment_identity():
    """The xp filter replaces the TTL+epoch heuristics when the frame
    carries identity: a mismatched entry is dropped outright, a matched
    one survives even an epoch bump; identity-less entries keep the old
    heuristic behavior."""
    node = Node(None, None)
    try:
        node.state.experiment_xid = "this-exp"
        stale = _update(1.0, ["p"])
        stale.xp = "previous-exp"
        fresh = _update(2.0, ["q"])
        fresh.xp = "this-exp"
        legacy = _update(3.0, ["r"])  # xp None: pre-xp sender
        node.stash_async_update(stale)
        node.stash_async_update(fresh)
        node.stash_async_update(legacy)
        # an epoch bump invalidates the heuristic path but NOT the exact one
        node.state.experiment_epoch += 1
        kept = node.take_async_stash()
        assert [u.xp for u, _src in kept] == ["this-exp"]
        # early-init filter: a mismatched init is dropped, a matched one
        # survives past the TTL
        init = _update(4.0, ["s"])
        init.xp = "previous-exp"
        node.stash_early_init(init)
        assert node.take_early_init() is None
        init2 = _update(5.0, ["s"])
        init2.xp = "this-exp"
        node.stash_early_init(init2)
        node._early_init = (node._early_init[0] - 10 * Settings.EARLY_INIT_TTL, init2)
        assert node.take_early_init() is init2
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# simulator: churn plans, replay, re-convergence, version monotonicity
# ---------------------------------------------------------------------------


def _churn_plan(n, seed=1905, kill_root=True):
    """~5% graceful+abrupt leaves, ~5% joins, one global-root kill.

    The root kill is a time-targeted ABRUPT leave (a killed process: no
    announcement, discovered one evict_delay later) at t=0.7 — inside
    the first convergence waterfall, while the root is the only node
    minting globals — so re-convergence genuinely crosses the failover
    window instead of the kill landing after the target.
    """
    addrs = [f"sim-{i:04d}" for i in range(n)]
    n_churn = max(2, n // 20)
    leaves = {
        a: LeaveSpec(at_s=0.4 + 0.03 * j, graceful=(j % 2 == 0))
        for j, a in enumerate(addrs[3 :: max(1, n // n_churn)][:n_churn])
    }
    joins = {
        f"sim-j{j:03d}": JoinSpec(at_s=0.6 + 0.05 * j) for j in range(n_churn)
    }
    if kill_root:
        leaves[addrs[0]] = LeaveSpec(at_s=0.7, graceful=False)
    return FaultPlan(
        seed=seed,
        default=EdgeFault(drop=0.01),
        joins=joins,
        leaves=leaves,
    )


def test_simfleet_churn_replay_bit_identical():
    """The full churn plan — joins, graceful AND abrupt leaves, a root
    kill — replays bit-exact from (seed, plan); a different seed
    diverges."""

    def run(seed):
        return SimulatedAsyncFleet(
            64,
            seed=seed,
            cluster_size=8,
            updates_per_node=6,
            slow_frac=0.1,
            slow_factor=8.0,
            plan=_churn_plan(64),
        ).run()

    a, b = run(42), run(42)
    assert a.version == b.version and a.version > 0
    np.testing.assert_array_equal(np.asarray(a.params["w"]), np.asarray(b.params["w"]))
    assert a.loss_curve == b.loss_curve
    assert a.joined == b.joined and a.left == b.left and a.crashed == b.crashed
    assert a.failovers == b.failovers and a.failovers >= 1
    assert a.joined and a.left  # the plan actually churned
    c = run(43)
    assert not np.array_equal(np.asarray(a.params["w"]), np.asarray(c.params["w"]))


def test_simfleet_1k_churn_reconverges_with_bounded_disruption():
    """ISSUE 11 acceptance: a 1k-node hierarchical fleet under the full
    churn plan (5% leave + 5% join + global-root kill) still reaches the
    loss target, joiners' contributions merge, and the minted version
    sequence is strictly monotone THROUGH the failover."""
    n = 1000
    static = SimulatedAsyncFleet(
        n, seed=7, cluster_size=32, updates_per_node=4, local_lr=0.7,
    )
    start_loss = static.loss_fn({"w": np.zeros(16, np.float32)})
    target = float(start_loss) * 0.05
    static.target_loss = target
    res_static = static.run()

    churn = SimulatedAsyncFleet(
        n, seed=7, cluster_size=32, updates_per_node=4, local_lr=0.7,
        plan=_churn_plan(n), target_loss=target,
    )
    res = churn.run()
    assert res.failovers >= 1, "the root kill never triggered a failover"
    assert len(res.joined) >= 50 and len(res.left) >= 50
    assert res.final_loss() < start_loss / 10, "churn fleet did not re-converge"
    assert res.time_to_target is not None, "churn fleet never hit the target"
    # bounded disruption: churn costs less than 3x the static fleet's
    # time-to-target (the bench quantifies the exact ratio)
    assert res_static.time_to_target is not None
    assert res.time_to_target < 3.0 * max(res_static.time_to_target, 1.0)
    # version monotonicity across the handover: the minted sequence in
    # the loss curve never repeats or regresses
    versions = [v for _t, v, _l in res.loss_curve]
    assert versions == sorted(versions) and len(set(versions)) == len(versions)


def test_root_killed_mid_flush_version_monotonicity():
    """The regression the high-water handover exists for: the root is
    killed right after minting versions its SUCCESSOR never saw (a
    one-way partition eats the root→successor model pushes). The
    successor must resume minting strictly above the corpse's last
    version — carried to it only inside the "vv" triples of updates
    trained from that version."""
    n = 6
    addrs = [f"sim-{i:04d}" for i in range(n)]
    plan = FaultPlan(
        seed=3,
        # successor (sim-0001) never receives a model push from the root
        partitions=[(addrs[0], addrs[1])],
        crashes={addrs[0]: CrashSpec(stage="AsyncTrainStage", round_no=3)},
    )
    fleet = SimulatedAsyncFleet(
        n, seed=3, cluster_size=0, k=2, updates_per_node=8, plan=plan,
        evict_delay=0.3,
    )
    res = fleet.run()
    assert res.failovers >= 1
    # the successor was blind to the root's mints before the kill...
    assert fleet.nodes[addrs[1]].known_version > 0
    # ...yet the minted sequence never regressed or repeated
    versions = [v for _t, v, _l in res.loss_curve]
    assert versions == sorted(versions) and len(set(versions)) == len(versions)
    # and minting continued after the failover (the curve outlived the corpse)
    pre_kill = max(v for t, v, _l in res.loss_curve if t < 3 * 0.8)
    assert res.version > pre_kill


# ---------------------------------------------------------------------------
# real nodes: root kill, mid-experiment join, graceful leave
# ---------------------------------------------------------------------------


def _mk_nodes(n, prefix=None):
    nodes = [
        Node(
            learner=DummyLearner(value=float(i)),
            address=f"{prefix}-{i}" if prefix else None,
        )
        for i in range(n)
    ]
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, n - 1, only_direct=True, wait=10)
    return nodes


def _stop_all(nodes):
    for n in nodes:
        n.stop()


def _sum_metric(metric):
    return sum(d.get(metric, 0.0) for d in logger.get_comm_metrics().values())


def _pace(seconds):
    """A stage hook that paces local updates so churn lands mid-run."""

    def hook(node, stage_name):
        if stage_name == "AsyncTrainStage":
            time.sleep(seconds)

    return hook


def test_async_root_kill_fails_over_to_successor():
    """ISSUE 11 acceptance (live half): the GLOBAL ROOT is killed
    mid-run — the next-sorted live regional self-elects as successor
    root, survivors keep merging and converge on one global, and nobody
    sits out the failover window."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 3
    nodes = _mk_nodes(6, prefix="rk")
    # addresses rk-0..rk-5 sort deterministically: clusters
    # [rk-0,rk-1,rk-2] + [rk-3,rk-4,rk-5]; rk-0 is the global root
    by_addr = {n.addr: n for n in nodes}
    root = by_addr[sorted(by_addr)[0]]
    plan = FaultPlan(
        seed=1905,
        crashes={root.addr: CrashSpec(stage="AsyncTrainStage", round_no=1)},
    )
    install_fault_plan(nodes, plan)
    for n in nodes:
        n.stage_hooks.append(_pace(0.4))
    survivors = [n for n in nodes if n is not root]
    try:
        t0 = time.monotonic()
        nodes[1].set_start_learning(rounds=6, epochs=1)
        wait_to_finish(survivors, timeout=60)
        elapsed = time.monotonic() - t0
        assert elapsed < 50.0, "a node sat out the failover window"
        assert not root._running
        for n in survivors:
            assert n.state.round is None
        # exactly one survivor self-elected as successor root
        assert _sum_metric("root_failover") >= 1
        assert _sum_metric("role_changed") >= 1
        assert _sum_metric("async_merge") >= 2
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        remove_fault_plan(nodes)
        _stop_all(nodes)


def test_async_join_mid_experiment():
    """A node JOINS a running experiment: it bootstraps from an
    aggregator's current global (async_pull), the fleet folds it into
    the topology, its updates merge, and it finishes on the fleet's
    final global."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 0
    nodes = _mk_nodes(4, prefix="jn-a")
    for n in nodes:
        n.stage_hooks.append(_pace(0.35))
    joiner = Node(learner=DummyLearner(value=99.0), address="jn-z-joiner")
    joiner.start()
    try:
        nodes[0].set_start_learning(rounds=8, epochs=1)
        time.sleep(1.0)  # the fleet is mid-run, globals already minted
        full_connection(joiner, nodes)
        wait_convergence([joiner], 4, only_direct=True, wait=10)
        joiner.join_async_experiment(rounds=2, epochs=1)
        wait_to_finish(nodes + [joiner], timeout=60)
        assert _sum_metric("async_join") == 1
        assert _sum_metric("async_pull_served") >= 1
        assert _sum_metric("membership_changed") >= 1
        # the joiner ends on the fleet's final global, not its own init
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in nodes]
        jp = np.asarray(joiner.learner.get_parameters()["w"])
        np.testing.assert_allclose(jp, params[0], atol=1e-5)
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        _stop_all(nodes + [joiner])


def test_weights_handlers_drop_cross_experiment_frames():
    """The xp gate on the DIRECT delivery path (not just the stashes): a
    previous experiment's retried async_update/async_model must never
    reach a fresh context's buffers — its version triple is unknown to
    the new version vector and would merge at full weight."""
    from p2pfl_tpu.federation import TierRouter as _TR
    from p2pfl_tpu.federation.workflow import AsyncContext

    node = Node(learner=DummyLearner(value=0.0))
    try:
        # a second (virtual) member keeps the flat K at 2, so a valid
        # offer BUFFERS instead of flushing immediately
        router = _TR([node.addr, "zz-peer"], 0)
        ctx = AsyncContext(node, router, {"w": np.zeros(4, np.float32)}, xid="exp2")
        stale = _update(9.0, ["ghost"], version=("ghost", 1, 0))
        stale.xp = "exp1"
        assert ctx.handle_update(stale) == []
        assert ctx.gbuf.pending() == 0
        assert _sum_metric("async_xp_filtered") >= 1
        stale_model = _update(9.0, ["ghost"], version=("ghost", 5, 5))
        stale_model.xp = "exp1"
        assert ctx.handle_model(stale_model, "ghost") == []
        assert ctx.global_version == 0, "cross-experiment global adopted"
        # a matching frame flows normally
        ok = _update(1.0, ["peer"], version=("peer", 1, 0))
        ok.xp = "exp2"
        ctx.handle_update(ok)
        assert ctx.gbuf.pending() == 1
    finally:
        node.stop()


def test_join_view_merge_restores_shared_chunking():
    """A joiner's live overlay view lacks the dead members survivors keep
    as cluster HOLES — deriving from it alone would chunk clusters
    differently from the fleet forever. Merging the pull server's
    (members, dead) view (async_view) restores the shared derivation."""
    from p2pfl_tpu.federation.workflow import AsyncContext

    node = Node(None, None)
    try:
        members = ["a", "b", "c", "d", "e", "f"]
        survivor = TierRouter(members + [node.addr], 3, dead={"c"})
        # the joiner never saw c: its own view is the live members only
        live_only = [m for m in members if m != "c"] + [node.addr]
        ctx = AsyncContext(node, TierRouter(live_only, 3), {"w": np.zeros(4, np.float32)})
        assert ctx.router.topo.clusters != survivor.topo.clusters
        ctx.merge_view(members + [node.addr], ["c"])
        assert ctx.router.topo.clusters == survivor.topo.clusters
        assert ctx.router.roles() == survivor.roles()
        # idempotent: merging the same view again changes nothing
        assert ctx.merge_view(members + [node.addr], ["c"]) == []
    finally:
        node.stop()


def test_overlay_presence_is_not_membership():
    """A node that CONNECTS mid-run without joining (a monitor, or a
    node waiting to call join_async_experiment) must not be folded into
    the topology — membership grows only on an async_join announcement,
    so a non-participant can never be elected aggregator and blackhole a
    tier."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 0
    nodes = _mk_nodes(4, prefix="np-m")
    for n in nodes:
        n.stage_hooks.append(_pace(0.25))
    # "np-a..." sorts BEFORE every member — under presence-based
    # membership it would be elected global root and blackhole the run
    monitor = Node(learner=DummyLearner(value=50.0), address="np-a-monitor")
    monitor.start()
    try:
        nodes[0].set_start_learning(rounds=4, epochs=1)
        time.sleep(0.6)
        full_connection(monitor, nodes)
        wait_to_finish(nodes, timeout=60)
        # membership never changed (no announcement, no eviction)...
        assert _sum_metric("membership_changed") == 0
        # ...and the fleet converged without routing anything at the monitor
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in nodes]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
        assert monitor.state.round is None and monitor._running
    finally:
        _stop_all(nodes + [monitor])


def test_async_graceful_leave():
    """A member LEAVES gracefully mid-run: it announces (async_leave),
    survivors re-derive around the hole without an eviction window, the
    fleet completes, and the leaver exits cleanly with its node still
    serving the overlay."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 0
    nodes = _mk_nodes(5, prefix="lv")
    for n in nodes:
        n.stage_hooks.append(_pace(0.35))
    leaver = nodes[3]
    try:
        nodes[0].set_start_learning(rounds=6, epochs=1)
        time.sleep(0.9)
        leaver.request_async_leave()
        wait_to_finish(nodes, timeout=60)
        assert _sum_metric("async_left") == 1
        assert _sum_metric("async_merge") >= 2
        assert leaver._running, "a graceful leave must not stop the node"
        assert leaver.state.round is None
        stayed = [n for n in nodes if n is not leaver]
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in stayed]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        _stop_all(nodes)
