"""Federation round hot-path overhaul (ISSUE 3): SCAFFOLD fast path,
per-phase round profiling, and the SPMD secure-aggregation design pin.

The chunked overlapped-staging parity lives in ``tests/test_chunked.py``;
together these suites are the CI smoke guard for the round pipeline
(.github/workflows/round_bench.yml).
"""

import jax
import jax.numpy as jnp
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import SpmdFederation
from p2pfl_tpu.settings import Settings


@pytest.fixture(autouse=True)
def _restore_knobs():
    yield
    Settings.SCAFFOLD_FUSED_CI = True
    Settings.SECURE_AGGREGATION = False


def _max_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def _scaffold_fed(data, **kw):
    return SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False,
        scaffold=True, optimizer="sgd", learning_rate=0.05, seed=3, **kw,
    )


def test_scaffold_fused_ci_matches_legacy():
    """The fast path derives c_i⁺ from the scan's fp32 grad mean; under
    plain SGD that is ALGEBRAICALLY identical to the legacy
    (x − y_i)/(K·η) anchor formula (option II, Karimireddy et al. 2020).
    Numerically the two differ only by fp32 rounding — the legacy formula
    divides a difference of large-magnitude params, the fused one never
    forms it — so the tolerance is rounding-scale, not algorithmic."""
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)

    def run(fused_ci):
        Settings.SCAFFOLD_FUSED_CI = fused_ci
        fed = _scaffold_fed(data)
        fed.run(rounds=2, epochs=2)
        return fed

    fast, legacy = run(True), run(False)
    assert _max_diff(fast.params, legacy.params) < 5e-3
    assert _max_diff(fast.c_global, legacy.c_global) < 5e-3
    assert _max_diff(fast.c_local, legacy.c_local) < 5e-3
    # and the variates actually moved off zero on both paths
    assert max(float(jnp.abs(x).max()) for x in jax.tree.leaves(fast.c_global)) > 0


def test_scaffold_fused_ci_matches_legacy_fused_span():
    """Same parity through spmd_rounds_fused (the scan-over-rounds program
    with the donated c_global/c_local carry)."""
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)

    def run(fused_ci):
        Settings.SCAFFOLD_FUSED_CI = fused_ci
        fed = _scaffold_fed(data)
        fed.run_fused(3, epochs=1)
        return fed

    fast, legacy = run(True), run(False)
    assert _max_diff(fast.params, legacy.params) < 5e-3
    assert _max_diff(fast.c_local, legacy.c_local) < 5e-3


def test_scaffold_fused_ci_partial_train_set_keeps_zero_variates():
    """Non-elected nodes' variates must stay exactly zero on the fast path
    too (the masked-commit logic is shared, but the fused ci⁺ flows through
    a different producer)."""
    import numpy as np

    old = Settings.TRAIN_SET_SIZE
    Settings.TRAIN_SET_SIZE = 2
    try:
        data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=4, batch_size=64, vote=True,
            scaffold=True, optimizer="sgd", learning_rate=0.05, seed=3,
        )
        fed.run_round(epochs=1)
        out_idx = np.flatnonzero(fed.train_mask == 0)
        assert len(out_idx) == 2
        for x in jax.tree.leaves(fed.c_local):
            assert float(jnp.abs(jnp.asarray(x)[out_idx]).max()) == 0.0
    finally:
        Settings.TRAIN_SET_SIZE = old


def test_profile_round_breakdown_keys_and_state():
    """profile_round attributes the round per phase and leaves the
    federation's round state (round counter, rng stream, params) intact —
    the next round must be byte-for-byte what it would have been."""
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    fed = _scaffold_fed(data)
    fed.run_round(epochs=1)

    twin = _scaffold_fed(data)
    twin.run_round(epochs=1)

    prof = fed.profile_round(epochs=1, iters=1)
    assert prof is fed.last_profile
    for key in ("total_s", "train_s", "correction_s", "aggregate_s"):
        assert key in prof and prof[key] >= 0.0, prof
    # nominally >= 1.0 (per-phase probing re-runs the round's pieces), but
    # both sides are single-shot wall-clock measurements on a shared CPU —
    # scheduler noise has been observed to dip the ratio to ~0.88 in a
    # loaded full-suite run, so assert with a noise margin: the real
    # contract is "profiling is not pathologically slower or faster"
    assert prof["overhead_x"] is None or prof["overhead_x"] >= 0.6

    # profiling consumed nothing: the profiled fed and its unprofiled twin
    # produce identical next rounds (same rng draws, same params)
    e1 = fed.run_round(epochs=1)
    e2 = twin.run_round(epochs=1)
    assert float(e1["train_loss"]) == float(e2["train_loss"])
    assert _max_diff(fed.params, twin.params) == 0.0


def test_run_round_profile_flag_stashes_breakdown():
    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=2, batch_size=64, vote=False, seed=3
    )
    assert fed.last_profile is None
    fed.run_round(epochs=1, profile=True)
    assert set(fed.last_profile) >= {"total_s", "train_s", "correction_s", "aggregate_s"}


def test_spmd_rejects_secure_aggregation():
    """Design pin (docs/design.md, "Secure aggregation and the SPMD
    runtime"): one mesh is one trust domain — SECURE_AGGREGATION is a
    gossip-plane protocol and the SPMD runtime must refuse it loudly
    instead of silently training unmasked."""
    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    Settings.SECURE_AGGREGATION = True
    with pytest.raises(ValueError, match="trust domain"):
        SpmdFederation.from_dataset(mlp(), data, n_nodes=2, batch_size=64)
    Settings.SECURE_AGGREGATION = False
    SpmdFederation.from_dataset(mlp(), data, n_nodes=2, batch_size=64)
