"""Chaos suite: seeded fault injection + self-healing rounds (ISSUE 5).

Everything here runs N real ``Node`` objects over the in-memory transport
with a committed :class:`FaultPlan` seed, so each scenario replays the same
chaos on every run:

- fault-plan determinism and edge semantics (drop/partition/scope),
- retry/backoff for failed control sends (silent message loss is gone),
- circuit-breaker suspects accelerating heartbeat eviction,
- stale-beat rejection (a relayed beat must not resurrect a dead node),
- mid-round train-set repair (survivors aggregate without burning the
  full ``AGGREGATION_TIMEOUT``),
- the pinned round-0 wedge regression (stale ``models_aggregated``
  redeliveries must not regress coverage views — see
  ``commands/control.py`` ModelsAggregatedCommand).
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    CrashSpec,
    EdgeFault,
    FaultInjector,
    FaultPlan,
    hard_crash,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.gossiper import Gossiper
from p2pfl_tpu.communication.heartbeater import BEAT_CMD
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.aggregators import FedAvg
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    yield
    MemoryRegistry.reset()


def _mk_nodes(n: int) -> list[Node]:
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(n)]
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, n - 1, only_direct=True, wait=10)
    return nodes


def _stop_all(nodes):
    for n in nodes:
        n.stop()


def _sum_metric(metric: str) -> float:
    return sum(d.get(metric, 0.0) for d in logger.get_comm_metrics().values())


# ---------------------------------------------------------------------------
# fault plan semantics
# ---------------------------------------------------------------------------


def test_fault_plan_replayable():
    """Same seed → the k-th send on an edge sees the same verdict; edges
    draw from independent streams."""

    def verdicts(plan, src, dst, k=64):
        inj = FaultInjector(plan, src)
        out = []
        for _ in range(k):
            ok = inj(dst, Message(src, "x"), False, lambda *a, **kw: True)
            out.append(ok)
        return out

    fault = EdgeFault(drop=0.5)
    a = verdicts(FaultPlan(seed=7, default=fault), "n1", "n2")
    b = verdicts(FaultPlan(seed=7, default=fault), "n1", "n2")
    assert a == b
    assert True in a and False in a  # p=0.5 over 64 draws
    other_edge = verdicts(FaultPlan(seed=7, default=fault), "n1", "n3")
    other_seed = verdicts(FaultPlan(seed=8, default=fault), "n1", "n2")
    assert a != other_edge and a != other_seed


def test_partition_and_scope():
    sent = []

    def transport(nei, env, create_connection=False):
        sent.append(env)
        return True

    # one-way partition: n1→n2 blocked, nothing reaches the transport
    plan = FaultPlan(seed=1, partitions=[("n1", "n2")])
    inj = FaultInjector(plan, "n1")
    assert inj("n2", Message("n1", "x"), False, transport) is False
    assert not sent
    # the reverse direction is untouched
    rev = FaultInjector(plan, "n2")
    assert rev("n1", Message("n2", "x"), False, transport) is True
    assert len(sent) == 1

    # scope="weights": control messages pass even at drop=1.0
    plan = FaultPlan(seed=1, default=EdgeFault(drop=1.0, scope="weights"))
    inj = FaultInjector(plan, "n1")
    assert inj("n2", Message("n1", "x"), False, transport) is True
    env = WeightsEnvelope("n1", 0, "add_model", ModelUpdate({"w": np.ones(2)}, ["n1"], 1))
    assert inj("n2", env, False, transport) is False


def test_duplicate_control_redelivery_has_fresh_id_and_ttl1():
    """A duplicated control message models a post-dedup-ring stale relay:
    fresh msg id (always re-accepted), ttl=1 (cannot re-amplify)."""
    delivered = []

    def transport(nei, env, create_connection=False):
        delivered.append(env)
        return True

    plan = FaultPlan(
        seed=3, default=EdgeFault(duplicate=1.0, duplicate_delay=0.05)
    )
    inj = FaultInjector(plan, "n1")
    orig = Message("n1", "models_aggregated", ("a", "b"), round=0, ttl=5)
    assert inj("n2", orig, False, transport) is True
    deadline = time.monotonic() + 2.0
    while len(delivered) < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(delivered) == 2, "duplicate copy never delivered"
    copy = delivered[1]
    assert copy.msg_id != orig.msg_id
    assert copy.ttl == 1
    assert copy.args == orig.args and copy.cmd == orig.cmd


# ---------------------------------------------------------------------------
# control-plane reliability: retry/backoff + circuit breaker
# ---------------------------------------------------------------------------


def test_message_retry_recovers_transient_failure():
    """A send that fails transiently is retried with backoff and delivered;
    the old behavior silently lost it."""
    attempts = []
    fail_first = 2

    def send_fn(nei, env, create_connection=False):
        attempts.append(nei)
        return len(attempts) > fail_first

    g = Gossiper("me", send_fn)
    g.start()
    try:
        g.add_message(Message("me", "vote", ("x", "1")), ["peer"])
        deadline = time.monotonic() + 5.0
        while len(attempts) < fail_first + 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(attempts) == fail_first + 1, "retries never delivered the message"
        m = logger.get_comm_metrics("me")
        assert m.get("msg_retry_scheduled", 0) == fail_first
        assert m.get("msg_retry_ok", 0) == 1
    finally:
        g.stop()


def test_message_retry_bounded_and_loud():
    """Retries are BOUNDED: a permanently failing neighbor costs exactly
    1 + MESSAGE_RETRY_MAX transport attempts, then the drop is counted."""
    attempts = []

    def send_fn(nei, env, create_connection=False):
        attempts.append(nei)
        return False

    g = Gossiper("me", send_fn)
    g.start()
    try:
        g.add_message(Message("me", "vote", ("x", "1")), ["peer"])
        deadline = time.monotonic() + 6.0
        while (
            logger.get_comm_metrics("me").get("msg_retry_exhausted", 0) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        m = logger.get_comm_metrics("me")
        assert m.get("msg_retry_exhausted", 0) == 1, "exhaustion never reported"
        assert len(attempts) == 1 + Settings.MESSAGE_RETRY_MAX
    finally:
        g.stop()


def test_beat_sends_never_enter_retry_queue():
    """Beats are exempt from the retry path at its single funnel
    (``schedule_retry``): a beat is superseded every HEARTBEAT_PERIOD, so
    retrying one would deliver stale liveness while crowding the per-tick
    budget during exactly the failure windows that matter."""
    g = Gossiper("me", lambda nei, env, create_connection=False: False)
    g.start()
    try:
        beat = Message("me", BEAT_CMD, (str(time.time()),))
        g.add_message(beat, ["peer"])
        deadline = time.monotonic() + 2.0
        while (
            logger.get_comm_metrics("me").get("gossip_send_fail", 0) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        g.schedule_retry("peer", beat, attempt=1)  # direct funnel: also exempt
        time.sleep(0.3)  # room for any (wrong) retry to get scheduled
        m = logger.get_comm_metrics("me")
        assert m.get("gossip_send_fail", 0) >= 1
        assert m.get("msg_retry_scheduled", 0) == 0
    finally:
        g.stop()


def test_breaker_suspect_accelerates_eviction():
    """Send failures open the per-neighbor breaker; a suspect is evicted
    after BREAKER_SUSPECT_TIMEOUT of beat silence instead of the full
    HEARTBEAT_TIMEOUT."""
    old_timeout = Settings.HEARTBEAT_TIMEOUT
    Settings.HEARTBEAT_TIMEOUT = 30.0  # make the slow path obviously slow
    nodes = _mk_nodes(2)
    a, b = nodes
    try:
        t0 = time.monotonic()
        hard_crash(b)  # no goodbyes: a finds out through send failures
        deadline = time.monotonic() + 10.0
        while b.addr in a.get_neighbors() and time.monotonic() < deadline:
            time.sleep(0.05)
        elapsed = time.monotonic() - t0
        assert b.addr not in a.get_neighbors(), "suspect never evicted"
        assert elapsed < 10.0 < Settings.HEARTBEAT_TIMEOUT
        m = logger.get_comm_metrics(a.addr)
        assert m.get("breaker_open", 0) >= 1
        assert m.get("breaker_suspect_evict", 0) >= 1
        assert m.get("neighbor_evicted", 0) >= 1
    finally:
        Settings.HEARTBEAT_TIMEOUT = old_timeout
        _stop_all(nodes)


def test_one_way_partition_evicts_despite_beats():
    """A neighbor we cannot send to — but whose beats still arrive — is
    evicted after a full HEARTBEAT_TIMEOUT of breaker-open: silence-based
    sweeps never fire for a one-way partition, so reachability has to be
    its own eviction clock.

    Three nodes, not two: with only a↔b, b would lose a's beats, evict a
    by silence, stop beating back — and a's *suspect* fast path would race
    the unreachable clock on the fresh silence. The third node keeps the
    flood alive (a's beats reach b via c), so b never goes silent toward a
    and the reachability clock is the only path that can fire. The suspect
    window is pinned above HEARTBEAT_TIMEOUT for the same reason: on a
    loaded box one beat delivery slipping past the (sub-second) suspect
    window would let the silence fast path fire first, turning the
    breaker_suspect_evict == 0 assertion into a scheduling race.
    """
    old_sus = Settings.BREAKER_SUSPECT_TIMEOUT
    Settings.BREAKER_SUSPECT_TIMEOUT = Settings.HEARTBEAT_TIMEOUT + 5.0
    nodes = _mk_nodes(3)
    a, b, c = nodes
    plan = FaultPlan(seed=5, partitions=[(a.addr, b.addr)])
    install_fault_plan([a], plan)  # only the a→b edge is severed
    try:
        deadline = time.monotonic() + Settings.HEARTBEAT_TIMEOUT + 8.0
        while (
            logger.get_comm_metrics(a.addr).get("breaker_unreachable_evict", 0) < 1
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        m = logger.get_comm_metrics(a.addr)
        assert m.get("breaker_unreachable_evict", 0) >= 1, (
            "one-way-partitioned peer never evicted"
        )
        # b's beats kept arriving at a the whole time — this was not a
        # silence eviction (neither the suspect fast path nor the plain
        # HEARTBEAT_TIMEOUT sweep fired for b)
        assert m.get("breaker_suspect_evict", 0) == 0
        assert c.addr in a.get_neighbors()  # the healthy edge is untouched
    finally:
        Settings.BREAKER_SUSPECT_TIMEOUT = old_sus
        remove_fault_plan([a])
        _stop_all(nodes)


def test_breaker_closes_on_success():
    from p2pfl_tpu.communication.reliability import CircuitBreaker

    br = CircuitBreaker("me")
    for _ in range(Settings.BREAKER_THRESHOLD):
        br.record("peer", False)
    assert br.is_suspect("peer")
    br.record("peer", True)
    assert not br.is_suspect("peer")
    m = logger.get_comm_metrics("me")
    assert m.get("breaker_open", 0) == 1 and m.get("breaker_close", 0) == 1


# ---------------------------------------------------------------------------
# heartbeater stale-beat rejection (satellite)
# ---------------------------------------------------------------------------


def test_stale_beat_rejected_fresh_beat_accepted():
    """A TTL-flooded beat relayed after its origin died must not refresh
    ``last_beat`` — regression test for the stale-beat fix."""
    nodes = _mk_nodes(2)
    a, b = nodes
    try:
        info = a.protocol.neighbors.get(b.addr)
        assert info is not None

        # stale origin stamp: rejected, last_beat untouched
        before = info.last_beat
        time.sleep(0.05)
        a.protocol.heartbeater.beat(
            b.addr, time.time() - Settings.HEARTBEAT_TIMEOUT - 1.0
        )
        assert a.protocol.neighbors.get(b.addr).last_beat == before
        assert logger.get_comm_metrics(a.addr).get("stale_beat_rejected", 0) >= 1

        # fresh origin stamp: accepted, last_beat refreshed
        a.protocol.heartbeater.beat(b.addr, time.time())
        assert a.protocol.neighbors.get(b.addr).last_beat > before

        # legacy beat with no origin info (t<=0): accepted for compatibility
        before = a.protocol.neighbors.get(b.addr).last_beat
        time.sleep(0.05)
        a.protocol.heartbeater.beat(b.addr, 0.0)
        assert a.protocol.neighbors.get(b.addr).last_beat > before
    finally:
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# mid-round train-set repair
# ---------------------------------------------------------------------------


def _update(addrs, value=1.0):
    return ModelUpdate({"w": np.full(4, value)}, list(addrs), len(addrs))


def test_discard_member_shrinks_target():
    agg = FedAvg(node_name="me")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(_update(["a"]))
    # c died before contributing: target shrinks to {a, b}
    assert agg.discard_member("c") == ["a"]
    assert agg.add_model(_update(["b"])) == ["a", "b"]
    result = agg.wait_and_get_aggregation(timeout=1.0)
    assert set(result.contributors) == {"a", "b"}
    assert logger.get_comm_metrics("me").get("train_set_repair", 0) == 1


def test_discard_member_keeps_arrived_contribution():
    agg = FedAvg(node_name="me")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(_update(["c"]))
    # c's training happened and its update is here — only ABSENCE is repaired
    assert agg.discard_member("c") is None
    agg.add_model(_update(["a"]))
    agg.add_model(_update(["b"]))
    result = agg.wait_and_get_aggregation(timeout=1.0)
    assert set(result.contributors) == {"a", "b", "c"}


def test_discard_member_closes_window_when_survivors_already_covered():
    agg = FedAvg(node_name="me")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(_update(["a"]))
    agg.add_model(_update(["b"]))
    assert not agg._complete.is_set()
    assert agg.discard_member("c") == ["a", "b"]
    assert agg._complete.is_set()
    result = agg.wait_and_get_aggregation(timeout=1.0)
    assert set(result.contributors) == {"a", "b"}


def test_discard_member_widens_waiting_acceptance():
    agg = FedAvg(node_name="me")
    agg.set_waiting_aggregated_model(["a", "b", "c"])
    # survivors-only aggregate rejected while c is still a live member
    assert agg.add_model(_update(["a", "b"])) == []
    assert agg.discard_member("c") is None  # widened, nothing to announce
    assert agg.add_model(_update(["a", "b"])) == ["a", "b"]


def test_waiting_all_members_discarded_still_requires_full_coverage():
    """Degenerate repair: every train-set member evicted while waiting must
    not collapse the acceptance interval to "anything" — a lone member's
    partial is still rejected; only a (post-partition-heal) full aggregate
    passes."""
    agg = FedAvg(node_name="me")
    agg.set_waiting_aggregated_model(["a", "b", "c"])
    for member in ("a", "b", "c"):
        agg.discard_member(member)
    assert agg.add_model(_update(["a"])) == []
    assert agg.add_model(_update(["a", "b"])) == []
    assert agg.add_model(_update(["a", "b", "c"])) == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# crash-at-stage + end-to-end self-healing federation
# ---------------------------------------------------------------------------


def test_crash_at_stage_no_goodbyes():
    """A CrashSpec kills the node like a killed process: peers still list
    it right after the crash and only evict via failure detection."""
    nodes = _mk_nodes(3)
    plan = FaultPlan(
        seed=11, crashes={nodes[2].addr: CrashSpec(stage="VoteTrainSetStage", round_no=0)}
    )
    install_fault_plan(nodes, plan)
    try:
        nodes[0].set_start_learning(rounds=1, epochs=0)
        deadline = time.monotonic() + 10.0
        while nodes[2]._running and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not nodes[2]._running, "crash spec never fired"
        # no disconnect messages went out: survivors still list the corpse
        # until heartbeat/breaker eviction does its job
        assert _sum_metric("fault_crash") == 1
        survivors = nodes[:2]
        wait_to_finish(survivors, timeout=30)
        deadline = time.monotonic() + 10.0
        while any(
            nodes[2].addr in n.get_neighbors() for n in survivors
        ) and time.monotonic() < deadline:
            time.sleep(0.05)
        for n in survivors:
            assert nodes[2].addr not in n.get_neighbors()
    finally:
        remove_fault_plan(nodes)
        _stop_all(nodes)


@pytest.mark.parametrize("n_nodes", [6, 8])
def test_chaos_federation_survives_slow_peer_and_midround_crash(n_nodes):
    """ISSUE 5 acceptance: N-node federation under 5% drop, one slow peer,
    one train-set member hard-crashing entering TrainStage. Every surviving
    node must finish every round — survivors aggregate via train-set repair
    within roughly one heartbeat-eviction window, nowhere near the full
    AGGREGATION_TIMEOUT — with bounded retries and zero stalls. The 6-node
    variant is the CI chaos smoke (chaos_smoke.yml); 8 nodes is the bench
    shape whose wedge started all of this."""
    Settings.TRAIN_SET_SIZE = n_nodes
    Settings.AGGREGATION_TIMEOUT = 60.0  # a repair failure would burn this
    Settings.STALL_WATCHDOG_S = 8.0  # make the zero-stall assertion real
    rounds = 2
    nodes = _mk_nodes(n_nodes)
    victim, slow = nodes[3], nodes[-1]
    plan = FaultPlan(
        seed=1905,
        default=EdgeFault(drop=0.05),
        slow_nodes={slow.addr: 0.3},
        crashes={victim.addr: CrashSpec(stage="TrainStage", round_no=0)},
    )
    install_fault_plan(nodes, plan)
    survivors = [n for n in nodes if n is not victim]
    try:
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=rounds, epochs=1)
        wait_to_finish(survivors, timeout=45)
        elapsed = time.monotonic() - t0
        # the crash was repaired, not waited out: well under the 60 s
        # aggregation timeout (the wall budget covers 2 full rounds plus
        # eviction latency under 5% drop + a 0.3 s/model slow peer)
        assert elapsed < 45.0
        assert not victim._running
        for n in survivors:
            assert n.state.round is None  # finished, back to idle
        assert _sum_metric("train_set_repair") >= 1, "no node repaired the train set"
        assert _sum_metric("stall_detected") == 0
        # retries are bounded, not a storm: every scheduled retry is backed
        # 1:1 by a definitive send failure (5% injected drop + sends to the
        # corpse until eviction — the latter surfacing as gossip_send_fail
        # on the dispatch path or send_fail_direct on protocol.send's), and
        # permanent failures exhaust after MESSAGE_RETRY_MAX instead of
        # climbing without bound
        failures = (
            _sum_metric("gossip_send_fail")
            + _sum_metric("send_fail_direct")
            + _sum_metric("fault_drop")
        )
        assert 0 < _sum_metric("msg_retry_scheduled") <= failures
        # the breaker saw the crash: suspects opened and fed early eviction
        assert _sum_metric("breaker_open") >= 1
        # survivors converged on the same repaired-aggregate parameters
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        from p2pfl_tpu.management.watchdog import StallWatchdog

        remove_fault_plan(nodes)
        _stop_all(nodes)
        StallWatchdog.shutdown()
        Settings.STALL_WATCHDOG_S = 0.0


# ---------------------------------------------------------------------------
# the pinned round-0 wedge regression
# ---------------------------------------------------------------------------

#: committed chaos seed reproducing the PR-4 "8-node slow-peer bench
#: federation occasionally wedges at round 0" flake on demand: stale
#: ``models_aggregated`` redeliveries (duplicates with fresh message ids —
#: exactly what TTL relays look like once the bounded dedup ring has
#: flooded out) arrive while a slow peer stretches the partial-gossip
#: phase. Under the pre-fix overwrite semantics the stale views regress
#: peers' coverage and the convergence detector never sees a static
#: status; with monotone union-merges the same chaos converges every run.
WEDGE_SEED = 1905


def test_round0_wedge_regression():
    old_ring = Settings.AMOUNT_LAST_MESSAGES_SAVED
    Settings.TRAIN_SET_SIZE = 6
    # small dedup ring: relays flood it out fast, like the 8-node bench
    Settings.AMOUNT_LAST_MESSAGES_SAVED = 20
    nodes = _mk_nodes(6)
    plan = FaultPlan(
        seed=WEDGE_SEED,
        default=EdgeFault(duplicate=0.5, duplicate_delay=0.4, scope="control"),
        slow_nodes={nodes[5].addr: 0.4},
    )
    install_fault_plan(nodes, plan)
    try:
        nodes[0].set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=40)
        for n in nodes:
            assert n.state.round is None
    finally:
        remove_fault_plan(nodes)
        Settings.AMOUNT_LAST_MESSAGES_SAVED = old_ring
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# the pinned kill-a-node-mid-startup wedge regression (ISSUE 9)
# ---------------------------------------------------------------------------

#: chaos seed for the startup-kill regression. The wedge needed no edge
#: faults at all — any node hard-crashing AFTER start_learning but BEFORE
#: casting its vote reproduced it: every survivor's VoteTrainSetStage
#: waited out the full VOTE_TIMEOUT (60 s at defaults) for the corpse's
#: vote, because the vote-collection loop snapshotted its candidate set at
#: stage entry and never re-checked liveness — while the PR-7 flight
#: record showed neighbor_evicted landing within the first two seconds
#: and 9+ s of retry backoff burned against the dead peer. ~1/3 of manual
#: probe runs hit it because the kill had to land in the pre-vote window.
STARTUP_WEDGE_SEED = 2206


def test_startup_kill_wedge_regression():
    """A node killed mid-startup (entering VoteTrainSetStage, i.e. before
    it votes) must delay the survivors by roughly one eviction window —
    NOT by VOTE_TIMEOUT. Pre-fix this takes > VOTE_TIMEOUT wall-clock;
    the bound asserts the whole 2-round run completes well inside it."""
    old_vote = Settings.VOTE_TIMEOUT
    Settings.VOTE_TIMEOUT = 30.0  # the pre-fix burn — generous vs the bound below
    nodes = _mk_nodes(5)
    victim = nodes[2]
    plan = FaultPlan(
        seed=STARTUP_WEDGE_SEED,
        crashes={victim.addr: CrashSpec(stage="VoteTrainSetStage", round_no=0)},
    )
    install_fault_plan(nodes, plan)
    survivors = [n for n in nodes if n is not victim]
    try:
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(survivors, timeout=25)
        elapsed = time.monotonic() - t0
        assert not victim._running, "crash spec never fired"
        # eviction window (~breaker suspect + heartbeat) + 2 fast rounds:
        # an order of magnitude under the 30 s VOTE_TIMEOUT the corpse's
        # vote would otherwise have burned
        assert elapsed < 15.0, f"startup kill still gates the vote ({elapsed:.1f}s)"
        for n in survivors:
            assert n.state.round is None
    finally:
        Settings.VOTE_TIMEOUT = old_vote
        remove_fault_plan(nodes)
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# StartLearningStage graceful abort (satellite)
# ---------------------------------------------------------------------------


def test_init_model_timeout_aborts_gracefully():
    """A node whose initial model never arrives clears the experiment and
    keeps serving — no TimeoutError escapes, and it can join the next
    start_learning normally."""
    old = Settings.AGGREGATION_TIMEOUT
    Settings.AGGREGATION_TIMEOUT = 1.0
    nodes = _mk_nodes(2)
    a, b = nodes
    try:
        # b learns it should start, but the initiator's init_model never
        # comes (nobody sends one): StartLearningStage must time out into a
        # graceful abort, not an escaping TimeoutError
        b._start_learning_thread(rounds=1, epochs=0)
        deadline = time.monotonic() + 10.0
        while b.state.status == "Learning" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert b.state.status == "Idle" and b.state.round is None
        assert b._running, "node stopped serving after init timeout"
        assert b.addr in a.get_neighbors()

        # and it joins the next experiment normally
        Settings.AGGREGATION_TIMEOUT = old
        a.set_start_learning(rounds=1, epochs=0)
        wait_to_finish(nodes, timeout=30, min_experiments=1)
    finally:
        Settings.AGGREGATION_TIMEOUT = old
        _stop_all(nodes)


def test_early_init_model_stash_consumed():
    """An init_model that arrives BEFORE start_learning (the weights plane
    can beat the TTL-flooded control broadcast) is stashed and consumed
    when the experiment starts — not dropped on the floor: the initiator's
    push loop exits once its status view stops changing, so a dropped
    early init may never be redelivered."""
    nodes = _mk_nodes(2)
    a, b = nodes
    try:
        upd = a.learner.get_model_update()
        # the init races ahead of b's start_learning: stashed, NOT latched
        b.protocol._commands["init_model"].execute(a.addr, 0, update=upd)
        assert not b.state.model_initialized_event.is_set()
        # the experiment starts: the stash seeds it instead of a timeout
        b._start_learning_thread(rounds=1, epochs=0)
        deadline = time.monotonic() + 5.0
        while (
            not b.state.model_initialized_event.is_set()
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert b.state.model_initialized_event.is_set(), "stash never consumed"
        expect = np.asarray(a.learner.get_parameters()["w"])
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if np.allclose(np.asarray(b.learner.get_parameters()["w"]), expect):
                break
            time.sleep(0.02)
        np.testing.assert_allclose(
            np.asarray(b.learner.get_parameters()["w"]), expect
        )
    finally:
        _stop_all(nodes)


def test_init_during_teardown_window_stashed_not_latched():
    """``state.clear()`` can run while the learning thread is still
    unwinding (the graceful abort clears before the workflow loop returns;
    ``stop_learning`` clears on the command thread mid-stage). A straggler
    ``init_model`` landing in that window must be STASHED, not latched —
    the thread-liveness gate alone passes there, and a latch after the
    clear would poison the next experiment, whose ``set_experiment``
    cannot re-clear the event (the initiator legitimately pre-sets it)."""
    old = Settings.AGGREGATION_TIMEOUT
    Settings.AGGREGATION_TIMEOUT = 3.0
    nodes = _mk_nodes(2)
    a, b = nodes
    try:
        b._start_learning_thread(rounds=1, epochs=0)
        deadline = time.monotonic() + 5.0
        while b.state.round is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b.state.round == 0, "experiment never entered StartLearningStage"
        # the teardown's clear() lands while the learning thread is alive
        b.state.clear()
        assert b.learning_active(), "window under test requires a live thread"
        b.protocol._commands["init_model"].execute(
            a.addr, 0, update=a.learner.get_model_update()
        )
        assert not b.state.model_initialized_event.is_set(), (
            "straggler init_model latched into a cleared experiment"
        )
        # the graceful abort then drains the stash, so the dead
        # experiment's init cannot seed the next one either
        deadline = time.monotonic() + 8.0
        while b.learning_active() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not b.learning_active()
        assert b.take_early_init() is None
    finally:
        Settings.AGGREGATION_TIMEOUT = old
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# eviction quarantine vs deliberate reconnects
# ---------------------------------------------------------------------------


def test_failed_direct_connect_preserves_quarantine():
    """A deliberate direct connect overrides quarantine only when it
    SUCCEEDS: a failed attempt must leave the quarantine entry in place,
    or the unreachable peer's very next beat re-admits it — the exact
    evict/re-add flap quarantine exists to prevent."""
    nodes = _mk_nodes(2)
    a, b = nodes
    na = a.protocol.neighbors
    try:
        na.evict(b.addr, quarantine=True)
        assert na.get(b.addr) is None
        # beats alone must not re-admit a quarantined peer
        na.heartbeat(b.addr)
        assert na.get(b.addr) is None
        # b vanishes (hard crash: unregistered, no goodbyes) — the connect
        # attempt fails and must NOT clear the quarantine
        b.protocol.crash()
        assert not a.protocol.connect(b.addr)
        na.heartbeat(b.addr)
        assert na.get(b.addr) is None, "failed connect cleared the quarantine"
    finally:
        _stop_all(nodes)


def test_successful_direct_connect_overrides_quarantine():
    nodes = _mk_nodes(2)
    a, b = nodes
    na = a.protocol.neighbors
    try:
        na.evict(b.addr, quarantine=True)
        na.heartbeat(b.addr)
        assert na.get(b.addr) is None
        # b is still reachable: the deliberate reconnect succeeds and lifts
        # the quarantine
        assert a.protocol.connect(b.addr)
        assert na.get(b.addr) is not None
        na.heartbeat(b.addr)
        assert na.get(b.addr) is not None
    finally:
        _stop_all(nodes)


def test_stale_breaker_evidence_does_not_evict():
    """The unreachable-despite-beats eviction requires ONGOING failure
    evidence: a breaker left open because the peer simply fell out of
    every send path (e.g. a non-direct gossip target the model plane
    converged away from) must not evict a live, beating neighbor on a
    stale burst — only fresh failures spanning the window count."""
    from p2pfl_tpu.communication.reliability import CircuitBreaker

    br = CircuitBreaker("me")
    for _ in range(Settings.BREAKER_THRESHOLD):
        br.record("peer", False)
    assert br.is_suspect("peer")
    time.sleep(0.3)
    # open for >= 0.25s, but the last failure is 0.3s old: with a 0.1s
    # freshness bound the evidence is stale — no eviction
    assert br.suspects_older_than(0.25, fresh_within=0.1) == set()
    # a fresh failure re-arms it
    br.record("peer", False)
    assert br.suspects_older_than(0.25, fresh_within=0.1) == {"peer"}
    # and without a freshness bound the old (pre-fix) semantics remain
    assert br.suspects_older_than(0.25) == {"peer"}


def test_models_aggregated_concurrent_merges_lose_nothing():
    """The union-merge must be atomic: handlers run on whatever thread
    delivers the message (sender gossip workers, duplicate timers), and
    an unlocked read-merge-write could clobber a concurrent merge for the
    same source — losing a sender's FINAL coverage announcement, which
    its exited push loop never repeats (the round-0 wedge, resurrected as
    a race)."""
    import threading as _threading

    nodes = _mk_nodes(1)
    (n,) = nodes
    try:
        n.state.round = 0
        cmd = n.protocol._commands["models_aggregated"]
        members = [f"m{i}" for i in range(8)]
        start = _threading.Barrier(4)

        def deliver(subset):
            start.wait()
            for _ in range(200):
                cmd.execute("peer", 0, *subset)

        threads = [
            _threading.Thread(target=deliver, args=(members[i * 2 : i * 2 + 2],))
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(n.state.models_aggregated["peer"]) == sorted(members)
    finally:
        n.state.round = None
        _stop_all(nodes)


def test_early_init_stash_expires_without_experiment():
    """A node that never starts an experiment must not hold a stashed
    init_model's parameters forever — the TTL fires on a timer, not only
    at take time."""
    old = Settings.EARLY_INIT_TTL
    Settings.EARLY_INIT_TTL = 0.2
    nodes = _mk_nodes(2)
    a, b = nodes
    try:
        b.protocol._commands["init_model"].execute(
            a.addr, 0, update=a.learner.get_model_update()
        )
        with b._early_init_lock:
            assert b._early_init is not None
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            with b._early_init_lock:
                if b._early_init is None:
                    break
            time.sleep(0.05)
        with b._early_init_lock:
            assert b._early_init is None, "stash never expired on an idle node"
    finally:
        Settings.EARLY_INIT_TTL = old
        _stop_all(nodes)
