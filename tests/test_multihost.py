"""Real multi-process ``init_multihost`` (VERDICT r4 #7): two CPU processes
form one JAX distributed runtime over localhost and run a global all-reduce
— the non-noop branches of ``parallel/distributed.py``, exercised without
TPU-pod hardware.

The worker runs in subprocesses because ``jax.distributed.initialize``
is once-per-process; the parent (which may already hold a backend) only
orchestrates.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the chip tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
pid = int(sys.argv[1])
os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:%PORT%"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)

from p2pfl_tpu.parallel.distributed import init_multihost

info = init_multihost()  # env-var path: the production bring-up
assert info["initialized"], info
assert info["process_count"] == 2, info
assert info["process_index"] == pid, info
assert info["global_devices"] == 2 * info["local_devices"], info

# one tiny global collective across the two processes: each contributes
# its process_index+1; the psum over the global mesh must see BOTH hosts
import jax
import jax.numpy as jnp
from jax.experimental.multihost_utils import process_allgather

got = process_allgather(jnp.float32(pid + 1))
assert sorted(got.tolist()) == [1.0, 2.0], got
print(f"OK process {pid}: {info['process_count']} procs, "
      f"{info['global_devices']} global devices, allgather {got.tolist()}")
"""


@pytest.mark.slow
def test_two_process_runtime_and_collective(tmp_path):
    import socket

    with socket.socket() as s:  # a free localhost port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.replace("%PORT%", str(port)))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_POOL_IPS")
    }
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.getcwd(), env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process runtime hung (coordinator never formed)")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    assert "OK process 0: 2 procs" in outs[0]
    assert "OK process 1: 2 procs" in outs[1]
