"""Real multi-process ``init_multihost`` (VERDICT r4 #7): two CPU processes
form one JAX distributed runtime over localhost and run a global all-reduce
— the non-noop branches of ``parallel/distributed.py``, exercised without
TPU-pod hardware.

The worker runs in subprocesses because ``jax.distributed.initialize``
is once-per-process; the parent (which may already hold a backend) only
orchestrates.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the chip tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
pid = int(sys.argv[1])
os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:%PORT%"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)

from p2pfl_tpu.parallel.distributed import init_multihost

info = init_multihost()  # env-var path: the production bring-up
assert info["initialized"], info
assert info["process_count"] == 2, info
assert info["process_index"] == pid, info
assert info["global_devices"] == 2 * info["local_devices"], info

# one tiny global collective across the two processes: each contributes
# its process_index+1; the psum over the global mesh must see BOTH hosts
import jax
import jax.numpy as jnp
from jax.experimental.multihost_utils import process_allgather

try:
    got = process_allgather(jnp.float32(pid + 1))
except Exception as e:  # jaxlib builds without CPU multiprocess computations
    if "aren't implemented" not in str(e):
        raise
    print(f"BACKEND-NO-MULTIPROC {pid}")
    sys.exit(0)
assert sorted(got.tolist()) == [1.0, 2.0], got
print(f"OK process {pid}: {info['process_count']} procs, "
      f"{info['global_devices']} global devices, allgather {got.tolist()}")
"""


_ROUND_WORKER = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the chip tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
pid = int(sys.argv[1])
os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:%PORT%"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)

from p2pfl_tpu.parallel.distributed import init_multihost

info = init_multihost()
assert info["initialized"] and info["process_count"] == 2, info

# one real federated round on the GLOBAL mesh: each process owns one node
# slot; the round's masked FedAvg reduce + diffusion cross the process
# boundary (DCN on a pod, the distributed runtime here). Both processes
# build identical host state (same seeds), so they dispatch the same
# program over the 2-device global mesh.
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.multihost_utils import process_allgather
from jax.sharding import Mesh

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import SpmdFederation

mesh = Mesh(np.array(jax.devices()), ("nodes",))
data = FederatedDataset.synthetic_mnist(n_train=128, n_test=32, seed=5)
try:
    fed = SpmdFederation.from_dataset(
        mlp(seed=0), data, n_nodes=2, mesh=mesh, batch_size=16, vote=False, seed=3
    )
    entry = fed.run_round(epochs=1)
except Exception as e:  # jaxlib builds without CPU multiprocess computations
    if "aren't implemented" not in str(e):
        raise
    print(f"BACKEND-NO-MULTIPROC {pid}")
    sys.exit(0)

@jax.jit
def probe(tree):
    leaves = jax.tree.leaves(tree)
    fp = sum(jnp.sum(jnp.abs(x.astype(jnp.float32))) for x in leaves)
    # diffusion check: both node slots hold the identical aggregate
    # (jnp.max over a stacked vector — Python max() can't compare tracers)
    slot_diff = jnp.max(jnp.stack([
        jnp.max(jnp.abs(x[0].astype(jnp.float32) - x[1].astype(jnp.float32)))
        for x in leaves
    ]))
    return fp, slot_diff

fp, slot_diff = probe(fed.params)
assert float(slot_diff) == 0.0, float(slot_diff)
loss = float(entry["train_loss"])
assert np.isfinite(loss), loss

# equal models on BOTH processes: every process sees the same replicated
# fingerprint, and the allgathered per-process readings agree exactly
# (host float first — allgather of an already-global array is identity)
got = process_allgather(jnp.float32(float(fp)))
assert got.shape == (2,) and float(got[0]) == float(got[1]), got
print(f"OK round process {pid}: loss {loss:.4f} fingerprint {float(fp):.6f}")
"""


_SHARDED_WORKER = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the chip tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
# two virtual devices per process: 4 global devices = 2 sharded nodes x
# model_parallel 2, with each node's slice INTERLEAVED across the hosts
flags = os.environ.get("XLA_FLAGS", "")
os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=2").strip()
pid = int(sys.argv[1])
os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:%PORT%"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)

from p2pfl_tpu.parallel.distributed import init_multihost

info = init_multihost()
assert info["initialized"] and info["process_count"] == 2, info
assert info["global_devices"] == 4, info

# the sharded-node witness: every node is a model_parallel=2 submesh that
# SPANS both hosts (device order [p0d0, p1d0] / [p0d1, p1d1]), so the
# row-parallel all-reduce inside each node's round AND the cross-slice
# aggregation fold both cross the process boundary (DCN on a pod). Both
# processes build identical host state (same seeds) and dispatch the same
# global programs — the multi-controller SPMD contract.
import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.multihost_utils import process_allgather

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import ShardedNodeFederation

devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
per_proc = [d for d in devs if d.process_index == 0], [d for d in devs if d.process_index == 1]
order = [per_proc[0][0], per_proc[1][0], per_proc[0][1], per_proc[1][1]]
rules = (
    (r"Dense_0/kernel", (None, "model")),
    (r"Dense_1/kernel", ("model", None)),
    (r"Dense_2/kernel", (None, "model")),
    (r".*", ()),
)
data = FederatedDataset.synthetic_mnist(n_train=128, n_test=16, seed=5)
try:
    fed = ShardedNodeFederation.from_dataset(
        mlp(seed=0), data, n_nodes=2, rules=rules, model_parallel=2,
        devices=order, batch_size=16, vote=False, seed=3,
    )
    for node_devs in (fed.slices[0], fed.slices[1]):
        procs = {d.process_index for d in np.asarray(node_devs.devices).flat}
        assert procs == {0, 1}, procs  # each node spans BOTH hosts
    entry = fed.run_round(epochs=1)
except Exception as e:  # jaxlib builds without CPU multiprocess computations
    if "aren't implemented" not in str(e):
        raise
    print(f"BACKEND-NO-MULTIPROC {pid}")
    sys.exit(0)

loss = float(entry["train_loss"])
assert np.isfinite(loss), loss

# the fold's psum saw BOTH slices: the stacked accumulator is sharded over
# the nodes axis and its total weight is both nodes' sample counts
psum_shardings = jax.tree.leaves(
    fed.last_fold["psum_shardings"], is_leaf=lambda x: hasattr(x, "spec")
)
assert all(s.spec[0] == "nodes" for s in psum_shardings), "fold input not node-sharded"
assert float(jnp.sum(fed.last_fold["wsum"])) == float(sum(fed._sizes))

# diffusion: both nodes hold the identical aggregate...
@jax.jit
def fingerprint(tree):
    return sum(jnp.sum(jnp.abs(x.astype(jnp.float32))) for x in jax.tree.leaves(tree))

fp0 = fingerprint(fed.node_params(0))
fp1 = fingerprint(fed.node_params(1))
assert float(fp0) == float(fp1), (float(fp0), float(fp1))
# ...and BOTH processes observe the same bits of it
got = process_allgather(jnp.float32(float(fp0)))
assert got.shape == (2,) and float(got[0]) == float(got[1]), got
print(f"OK sharded process {pid}: loss {loss:.4f} fingerprint {float(fp0):.6f}")
"""


def _run_two_process_workers(tmp_path, worker_src, ok_marker, timeout=240):
    import socket

    with socket.socket() as s:  # a free localhost port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(worker_src.replace("%PORT%", str(port)))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_POOL_IPS")
    }
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.getcwd(), env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process runtime hung (coordinator never formed)")
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    if all("BACKEND-NO-MULTIPROC" in out for out in outs):
        # the runtime FORMED (both workers initialized, saw 2 procs and the
        # global device view — asserted in-worker) but this jaxlib's CPU
        # backend cannot run cross-process computations. Since
        # init_multihost switched the CPU world onto gloo collectives
        # (parallel/distributed.py _enable_cpu_collectives — the DCN
        # plane's CI substrate, test_dcn_plane.py), this branch is
        # vestigial on the shipped toolchain: it only fires on jaxlib
        # builds without a gloo/mpi CPU collectives implementation
        pytest.skip("jaxlib CPU backend lacks multiprocess computations")
    for pid, out in enumerate(outs):
        assert f"{ok_marker} {pid}" in out, out[-2000:]
    return outs


@pytest.mark.slow
def test_two_process_runtime_and_collective(tmp_path):
    _run_two_process_workers(tmp_path, _WORKER, "OK process")


@pytest.mark.slow
def test_two_process_federated_round_equal_models(tmp_path):
    """The executable witness for the DCN story (parallel/spmd_lm.py):
    a 2-node federated round over the 2-process global mesh — train,
    cross-process FedAvg reduce, diffusion — ends with the identical
    aggregated model on both processes."""
    _run_two_process_workers(tmp_path, _ROUND_WORKER, "OK round process")


@pytest.mark.slow
def test_two_process_sharded_node_round(tmp_path):
    """The sharded-node witness (ISSUE 10): two ``model_parallel=2``
    submesh nodes whose slices each SPAN both processes' devices — the
    in-round row-parallel all-reduce and the cross-slice aggregation
    psum both cross the process boundary, and both processes end holding
    the identical diffused aggregate. Backend-gated like the allgather
    test (CPU jaxlib without multiprocess computations skips)."""
    _run_two_process_workers(tmp_path, _SHARDED_WORKER, "OK sharded process")
