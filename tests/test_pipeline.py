"""Pipeline parallelism (`parallel/pipeline.py`): GPipe schedule over a
mesh axis. The reference has no pipeline parallelism (SURVEY §2.9); parity
is asserted against sequential execution of the same layers."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
from p2pfl_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_mesh,
    pipelined_lm_apply,
    stack_layers,
)


def _toy_layers(n_layers=4, dim=16, seed=0):
    key = jax.random.PRNGKey(seed)
    layers = []
    for _ in range(n_layers):
        k1, k2, key = jax.random.split(key, 3)
        layers.append(
            {
                "w": jax.random.normal(k1, (dim, dim)) * 0.3,
                "b": jax.random.normal(k2, (dim,)) * 0.1,
            }
        )
    return layers


def _apply_toy(p, act):
    return jnp.tanh(act @ p["w"] + p["b"])


def _sequential(layers, x):
    for p in layers:
        x = jax.vmap(lambda xx, p=p: _apply_toy(p, xx))(x)
    return x


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 8), (2, 6)])
@pytest.mark.slow
def test_pipeline_forward_matches_sequential(n_stages, n_micro):
    layers = _toy_layers(n_layers=n_stages * 2 if n_stages == 2 else n_stages)
    x = jax.random.normal(jax.random.PRNGKey(9), (n_micro, 4, 16))
    out = pipeline_apply(stack_layers(layers), x, _apply_toy, pipeline_mesh(n_stages))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_sequential(layers, x)), atol=1e-5, rtol=1e-5
    )


@pytest.mark.slow
def test_pipeline_grads_match_sequential():
    layers = _toy_layers()
    stacked = stack_layers(layers)
    mesh = pipeline_mesh(4)
    x = jax.random.normal(jax.random.PRNGKey(3), (6, 4, 16))

    def loss(sp):
        return jnp.sum(pipeline_apply(sp, x, _apply_toy, mesh) ** 2)

    def loss_ref(ls):
        return jnp.sum(_sequential(ls, x) ** 2)

    g = jax.grad(loss)(stacked)
    g_ref = stack_layers(jax.grad(loss_ref)(layers))
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_pipeline_rejects_indivisible_layers():
    layers = _toy_layers(n_layers=3)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 2, 16))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(stack_layers(layers), x, _apply_toy, pipeline_mesh(4))


def _lm_cfg():
    # f32 so pipelined-vs-monolithic comparison is not at the mercy of
    # bf16 reduction order
    return TransformerConfig(
        vocab_size=64,
        dim=32,
        n_layers=4,
        n_heads=2,
        n_kv_heads=2,
        ffn_hidden=64,
        lora_rank=0,
        dtype=jnp.float32,
    )


@pytest.mark.slow
def test_pipelined_transformer_matches_monolithic():
    cfg = _lm_cfg()
    m = tiny_transformer(seq_len=16, cfg=cfg)
    mesh = pipeline_mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
    ref = m.apply(m.params, tokens)
    out = pipelined_lm_apply(m.params, tokens, cfg, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_pipelined_moe_aux_flows():
    """MoE blocks in the pipeline: router losses are collected per stage and
    router grads flow; silently dropping aux is rejected."""
    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=4, n_heads=2, n_kv_heads=2,
        ffn_hidden=64, lora_rank=0, n_experts=4, dtype=jnp.float32,
    )
    m = tiny_transformer(seq_len=16, cfg=cfg)
    mesh = pipeline_mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (8, 16), 0, 64)

    with pytest.raises(ValueError, match="return_aux"):
        pipelined_lm_apply(m.params, tokens, cfg, mesh)

    logits, aux = pipelined_lm_apply(m.params, tokens, cfg, mesh, return_aux=True)
    assert logits.shape == (8, 16, 64)
    assert float(aux) > 0.0

    targets = jnp.roll(tokens, -1, axis=1)

    def loss(p):
        lo, a = pipelined_lm_apply(p, tokens, cfg, mesh, return_aux=True)
        return optax.softmax_cross_entropy_with_integer_labels(lo, targets).mean() + a

    g = jax.grad(loss)(m.params)
    router_gs = [
        v
        for kp, v in jax.tree_util.tree_leaves_with_path(g)
        if "router" in "/".join(str(getattr(q, "key", q)) for q in kp)
    ]
    assert router_gs and all(float(jnp.abs(v).max()) > 0 for v in router_gs)


@pytest.mark.slow
def test_pipelined_transformer_train_step():
    cfg = _lm_cfg()
    m = tiny_transformer(seq_len=16, cfg=cfg)
    mesh = pipeline_mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
    targets = jnp.roll(tokens, -1, axis=1)

    def loss(p):
        logits = pipelined_lm_apply(p, tokens, cfg, mesh)
        return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()

    # grads of the pipelined loss match the monolithic model's grads
    def loss_ref(p):
        logits = m.apply(p, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()

    g = jax.grad(loss)(m.params)
    g_ref = jax.grad(loss_ref)(m.params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3)

    tx = optax.adam(1e-2)
    opt = tx.init(m.params)
    params = m.params
    l0 = None

    @jax.jit
    def step(p, o):
        l, g = jax.value_and_grad(loss)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l

    for i in range(8):
        params, opt, l = step(params, opt)
        if i == 0:
            l0 = float(l)
    assert float(l) < l0
