"""DP-SGD local steps + RDP accountant (`learning/privacy.py`).

The reference has no privacy mechanism (SURVEY §2 — no clip/noise/dp
anywhere); DP-SGD is the standard defense against gradient leakage of
client data in FL."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.privacy import (
    PrivacyAccountant,
    clip_by_global_norm,
    dp_grads,
)
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import SpmdFederation


def test_clip_by_global_norm():
    g = {"a": jnp.full((4,), 3.0), "b": jnp.full((9,), 4.0)}  # norm = sqrt(36+144)
    clipped = clip_by_global_norm(g, 1.0)
    norm = math.sqrt(sum(float(jnp.sum(x * x)) for x in jax.tree.leaves(clipped)))
    assert abs(norm - 1.0) < 1e-5
    # already-small grads pass through unchanged
    small = {"a": jnp.full((4,), 0.01)}
    out = clip_by_global_norm(small, 1.0)
    np.testing.assert_allclose(np.asarray(out["a"]), 0.01, rtol=1e-6)


def test_dp_grads_clip_bounds_sensitivity():
    """With noise=0 the DP estimator's norm is bounded by clip (mean of
    per-example clipped grads) — the sensitivity the accountant assumes."""
    params = {"w": jnp.zeros((8,))}

    def loss_one(p, xi, yi):
        return 1e6 * jnp.sum(p["w"] * xi) + jnp.sum(xi) * 0.0 + 1e6 * jnp.sum(p["w"]) * yi

    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8))
    y = jnp.ones((16,))
    g, loss = dp_grads(loss_one, params, x, y, clip=1.0, noise=0.0, key=jax.random.PRNGKey(1))
    assert bool(jnp.isfinite(loss))
    norm = float(jnp.sqrt(sum(jnp.sum(v * v) for v in jax.tree.leaves(g))))
    assert norm <= 1.0 + 1e-5


def test_dp_grads_noise_changes_with_key():
    params = {"w": jnp.zeros((4,))}

    def loss_one(p, xi, yi):
        return jnp.sum(p["w"] * xi)

    x = jnp.ones((8, 4))
    y = jnp.zeros((8,))
    g1, _ = dp_grads(loss_one, params, x, y, 1.0, 1.0, jax.random.PRNGKey(1))
    g2, _ = dp_grads(loss_one, params, x, y, 1.0, 1.0, jax.random.PRNGKey(2))
    assert float(jnp.abs(g1["w"] - g2["w"]).max()) > 0.0


def test_accountant_monotone_and_sane():
    acc = PrivacyAccountant(noise=1.1, q=0.01)
    acc.step(100)
    e1 = acc.epsilon(1e-5)
    acc.step(900)
    e2 = acc.epsilon(1e-5)
    assert 0 < e1 < e2  # more steps, more privacy spent
    # more noise => less epsilon for the same steps
    quieter = PrivacyAccountant(noise=2.0, q=0.01)
    quieter.step(1000)
    assert quieter.epsilon(1e-5) < e2
    # full-batch (q=1) uses the plain Gaussian-mechanism RDP
    full = PrivacyAccountant(noise=1.0, q=1.0)
    full.step(1)
    assert full.epsilon(1e-5) > 0

    with pytest.raises(ValueError):
        PrivacyAccountant(noise=0.0, q=0.5)


@pytest.mark.slow
def test_dp_learner_trains_and_accounts():
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    learner = JaxLearner(mlp(), data, epochs=2, batch_size=64, dp_clip=1.0, dp_noise=1.0)
    learner.fit()
    assert learner.evaluate()["test_acc"] > 0.3  # learns despite the noise
    assert learner.accountant is not None
    assert learner.accountant.steps == 2 * (512 // 64)
    assert learner.accountant.epsilon(1e-5) > 0


@pytest.mark.slow
def test_spmd_dp_federation_learns():
    data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False, dp_clip=1.0, dp_noise=0.5
    )
    fed.run_round(epochs=1)  # per-round path
    entries = fed.run_fused(3, epochs=1, eval=True)  # fused path
    assert float(entries[-1]["test_acc"]) > 0.3
    assert fed.round == 4


def test_dp_noise_without_clip_rejected():
    """noise without a clip bound has no privacy semantics and would be
    silently ignored by the dp_clip-gated paths — must raise."""
    data = FederatedDataset.synthetic_mnist(n_train=128, n_test=32)
    with pytest.raises(ValueError, match="dp_clip"):
        JaxLearner(mlp(), data, dp_noise=1.0)
    with pytest.raises(ValueError, match="dp_clip"):
        SpmdFederation.from_dataset(mlp(), data, n_nodes=2, batch_size=32, dp_noise=1.0)


@pytest.mark.slow
def test_spmd_dp_accountant_tracks_rounds():
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False, dp_clip=1.0, dp_noise=1.0
    )
    assert fed.accountant is not None and fed.accountant.steps == 0
    fed.run_round(epochs=2)
    steps_one = fed.accountant.steps
    assert steps_one == 2 * fed._nb
    fed.run_fused(3, epochs=1)
    assert fed.accountant.steps == steps_one + 3 * fed._nb
    assert fed.accountant.epsilon(1e-5) > 0


def test_fedopt_on_result_then_aggregate():
    """A node whose first round resolves via a peer's diffused aggregate
    (on_result) must still be able to aggregate itself next round."""
    from p2pfl_tpu.learning.aggregators import FedAdam
    from p2pfl_tpu.learning.weights import ModelUpdate

    agg = FedAdam("me")
    # round 1 resolves via a consensus aggregate from a faster peer
    consensus = ModelUpdate({"w": jnp.full((4,), 0.5)}, ["me", "peer"], 20)
    agg.on_result(consensus)
    # round 2: this node aggregates individual models itself — must not crash
    r = agg.aggregate(
        [
            ModelUpdate({"w": jnp.full((4,), 0.2)}, ["me"], 10),
            ModelUpdate({"w": jnp.full((4,), 0.4)}, ["peer"], 10),
        ]
    )
    assert bool(jnp.isfinite(r.params["w"]).all())
    assert agg._t == 1  # server stepped off the adopted consensus x_t


@pytest.mark.slow
def test_spmd_dp_noise_perturbs_aggregate():
    """Same seed, dp on vs off: aggregates must differ (noise is real)."""
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    fa = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=2, batch_size=64, vote=False, seed=5
    )
    fb = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=2, batch_size=64, vote=False, seed=5,
        dp_clip=1.0, dp_noise=1.0,
    )
    fa.run_round(epochs=1)
    fb.run_round(epochs=1)
    diff = max(
        float(jnp.abs(a - b).max())
        for a, b in zip(jax.tree.leaves(fa.params), jax.tree.leaves(fb.params))
    )
    assert diff > 1e-4
