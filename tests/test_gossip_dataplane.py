"""Encode-once payload cache + concurrent gossip fan-out (data plane).

Covers the gossip data-plane contract (``learning/weights.py`` module docs,
``communication/gossiper.py``): payload bytes are encoded once per model
version and reused across candidates/ticks; the cache is invalidated by
``set_parameters``/``fit``; a topk8 error-feedback round folds the residual
exactly once; and a stalled peer costs one send-worker slot, never the tick.
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.gossiper import Gossiper
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning import weights as W
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import DummyLearner, JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate, PayloadCache
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import check_equal_models, full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    yield
    MemoryRegistry.reset()
    Settings.WIRE_COMPRESSION = "none"
    Settings.MEMORY_WIRE_CODEC = False
    Settings.GOSSIP_PAYLOAD_CACHE = True
    Settings.GOSSIP_SEND_WORKERS = 4
    Settings.GOSSIP_SEND_TIMEOUT = 2.0


# ---- payload cache ----


def test_payload_bytes_identical_across_candidates():
    """Within one model version, every candidate gets the SAME bytes and
    the encode pipeline runs once (cache hits for the rest)."""
    learner = DummyLearner()
    learner.set_addr("cache-node")
    before = W.encode_call_count()
    payloads = []
    for _ in range(5):  # five candidates, as a gossip tick would fetch
        update = learner.get_model_update()
        update.cache_round = 0
        payloads.append(update.encode())
    assert all(p is payloads[0] for p in payloads[1:])
    assert W.encode_call_count() - before == 1
    metrics = logger.get_comm_metrics("cache-node")
    assert metrics["encode_cache_hit"] == 4
    assert metrics["encode_cache_miss"] == 1


def test_cache_invalidated_on_set_parameters_and_fit():
    learner = DummyLearner()
    learner.set_addr("inval-node")
    u0 = learner.get_model_update()
    u0.cache_round = 0
    b0 = u0.encode()

    learner.fit()  # bumps the model version
    u1 = learner.get_model_update()
    u1.cache_round = 0
    b1 = u1.encode()
    assert b1 != b0

    learner.set_parameters(learner.get_parameters())  # bump even on same values
    u2 = learner.get_model_update()
    u2.cache_round = 0
    before = W.encode_call_count()
    u2.encode()
    assert W.encode_call_count() - before == 1  # fresh encode, not a replay


def test_subclass_learners_bump_model_version():
    """Every learner whose fit/set_parameters override bypasses JaxLearner
    must bump the model version itself — a missed bump makes the payload
    cache replay STALE bytes (e.g. untrained adapters gossiped as the
    round's trained contribution)."""
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.lora import LoRALearner
    from p2pfl_tpu.learning.personalization import PersonalizedLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
        ffn_hidden=64, lora_rank=4,
    )
    data = FederatedDataset.synthetic_lm(
        vocab_size=64, seq_len=16, n_train=16, n_test=8
    )
    lora = LoRALearner(tiny_transformer(seq_len=16, cfg=cfg), data, batch_size=8, epochs=1)
    v0 = lora.model_version
    lora.fit()
    assert lora.model_version > v0, "LoRALearner.fit must bump the version"
    v1 = lora.model_version
    lora.set_parameters(lora.get_parameters())
    assert lora.model_version > v1, "LoRALearner.set_parameters must bump"

    mnist = FederatedDataset.synthetic_mnist(n_train=64, n_test=16)
    pers = PersonalizedLearner(
        mlp(seed=0), mnist, batch_size=32, personal=("Dense_2",)
    )
    v0 = pers.model_version
    pers.set_parameters(pers.params)
    assert pers.model_version > v0, "PersonalizedLearner.set_parameters must bump"


def test_cache_disabled_reencodes_per_send():
    Settings.GOSSIP_PAYLOAD_CACHE = False
    learner = DummyLearner()
    learner.set_addr("nocache-node")
    before = W.encode_call_count()
    for _ in range(3):
        update = learner.get_model_update()
        update.cache_round = 0
        update.encode()
    assert W.encode_call_count() - before == 3


def _topk_update(params, anchor, residual, cache, version):
    update = ModelUpdate(params, ["a"], 1)
    update.anchor = anchor
    update.anchor_tag = "0:1"
    update.ef_residual = residual
    update.payload_cache = cache
    update.cache_version = version
    update.cache_round = 1
    return update


def test_topk_residual_folded_exactly_once_per_version():
    """Repeat sends of the own contribution must reuse the bytes instead of
    re-folding (and re-mutating) the error-feedback residual; a version bump
    re-encodes against the accumulated residual."""
    Settings.WIRE_COMPRESSION = "topk8"
    rng = np.random.default_rng(0)
    anchor = {"w": rng.normal(size=(64, 32)).astype(np.float32)}
    params = {"w": anchor["w"] + rng.normal(size=(64, 32)).astype(np.float32) * 0.1}
    residual: dict = {}
    cache = PayloadCache("topk-node")

    b1 = _topk_update(params, anchor, residual, cache, version=1).encode()
    assert residual, "first encode must populate the residual store"
    snapshot = {k: v.copy() for k, v in residual.items()}

    b2 = _topk_update(params, anchor, residual, cache, version=1).encode()
    assert b2 == b1
    for k in residual:  # cache hit ⇒ store untouched
        np.testing.assert_array_equal(residual[k], snapshot[k])

    b3 = _topk_update(params, anchor, residual, cache, version=2).encode()
    assert b3 != b1  # re-encode folds the accumulated residual
    assert any(not np.array_equal(residual[k], snapshot[k]) for k in residual)


# ---- concurrent fan-out ----


def test_stalled_peer_does_not_serialize_the_tick():
    """One peer hangs longer than GOSSIP_SEND_TIMEOUT: the other candidates'
    sends complete immediately and the tick returns within the budget."""
    Settings.GOSSIP_SEND_TIMEOUT = 0.5
    delivered: list[str] = []
    stall = 3.0

    def send_fn(nei, env, create_connection=False):
        if nei == "slow":
            time.sleep(stall)
        delivered.append(nei)
        return True

    gossiper = Gossiper("fanout-node", send_fn)
    gossiper.start()
    try:
        ticks = iter([["slow", "fast-1", "fast-2", "fast-3"], []])
        t0 = time.monotonic()
        gossiper.gossip_weights(
            early_stopping_fn=lambda: False,
            get_candidates_fn=lambda: next(ticks),
            status_fn=lambda: None,
            model_fn=lambda nei: f"payload-for-{nei}",
            period=0.01,
        )
        elapsed = time.monotonic() - t0
    finally:
        gossiper.stop()
    assert elapsed < stall, f"tick serialized behind the stalled peer ({elapsed:.2f}s)"
    assert {"fast-1", "fast-2", "fast-3"} <= set(delivered)
    metrics = logger.get_comm_metrics("fanout-node")
    assert metrics.get("gossip_send_timeout", 0) >= 1
    assert metrics.get("gossip_send_ok", 0) >= 3


def test_inflight_peer_skipped_not_stacked():
    """While a send to a peer is stuck past its budget, later batches skip
    that peer instead of stranding another worker behind the same stall."""
    Settings.GOSSIP_SEND_TIMEOUT = 0.2
    release = time.monotonic() + 1.5

    def send_fn(nei, env, create_connection=False):
        if nei == "slow":
            time.sleep(max(0.0, release - time.monotonic()))
        return True

    gossiper = Gossiper("inflight-node", send_fn)
    gossiper.start()
    try:
        first, first_skipped = gossiper._dispatch_sends([("slow", "p"), ("fast", "p")])
        assert first == [None, True]  # slow timed out, fast landed
        assert first_skipped == []
        second, second_skipped = gossiper._dispatch_sends([("slow", "p2"), ("fast", "p2")])
        assert second == [False, True]  # slow skipped while still in flight
        # skipped sends are reported so the message plane can requeue them
        assert second_skipped == [("slow", "p2")]
    finally:
        gossiper.stop()
    metrics = logger.get_comm_metrics("inflight-node")
    assert metrics.get("gossip_send_inflight_skip", 0) >= 1


# ---- end to end over the byte path ----


def _federation(n=3, aggregator=None):
    full = FederatedDataset.synthetic_mnist(n_train=768, n_test=128)
    nodes = []
    for i in range(n):
        learner = JaxLearner(mlp(seed=i), full.partition(i, n), batch_size=64)
        nodes.append(Node(learner=learner, aggregator=aggregator))
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, n - 1, only_direct=True)
    return nodes


def test_memory_wire_codec_federation_converges_with_cache():
    """The full byte path in-process: payloads are encoded (once per
    version), shipped, decoded and the federation still converges; the
    cache's effect is visible through the logger's comm metrics."""
    Settings.MEMORY_WIRE_CODEC = True
    nodes = _federation(3)
    try:
        before = W.encode_call_count()
        nodes[0].set_start_learning(rounds=1, epochs=0)
        wait_to_finish(nodes, timeout=90)
        check_equal_models(nodes)
        encodes = W.encode_call_count() - before
        hits = sum(
            m.get("encode_cache_hit", 0) for m in logger.get_comm_metrics().values()
        )
        sends = sum(
            m.get("gossip_send_ok", 0) for m in logger.get_comm_metrics().values()
        )
        assert hits > 0, "byte path never hit the payload cache"
        # encode-once: total encodes stay far below one-per-send
        assert encodes < hits + sends, (encodes, hits, sends)
    finally:
        for node in nodes:
            node.stop()


def test_stalled_memory_peer_does_not_block_round():
    """A peer whose receive path hangs past GOSSIP_SEND_TIMEOUT must not
    stop the others from finishing the round."""
    Settings.MEMORY_WIRE_CODEC = True
    Settings.GOSSIP_SEND_TIMEOUT = 0.5
    nodes = _federation(3)
    slow = nodes[2]
    orig = slow.protocol.handle_weights

    def slow_handle(env):
        time.sleep(1.2)
        return orig(env)

    slow.protocol.handle_weights = slow_handle
    try:
        nodes[0].set_start_learning(rounds=1, epochs=0)
        wait_to_finish(nodes, timeout=90)
        timeouts = sum(
            m.get("gossip_send_timeout", 0) for m in logger.get_comm_metrics().values()
        )
        assert timeouts >= 1, "stall never tripped the per-send budget"
    finally:
        slow.protocol.handle_weights = orig
        for node in nodes:
            node.stop()
