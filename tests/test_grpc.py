"""gRPC transport tests: real sockets on loopback, OS-assigned ports —
the reference's own multi-node test mechanism (SURVEY §4)."""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.grpc_transport import (
    GrpcProtocol,
    decode_message,
    decode_weights,
    encode_message,
    encode_weights,
)
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import DummyLearner, JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate, encode_params
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import wait_convergence, wait_to_finish, check_equal_models


def _grpc_node(**kwargs) -> Node:
    node = Node(protocol=GrpcProtocol("127.0.0.1:0"), **kwargs)
    node.start()
    return node


def test_codec_roundtrip():
    msg = Message("1.2.3.4:5", "vote_train_set", ("a", "1", "b", "2"), round=3, ttl=7)
    back = decode_message(encode_message(msg))
    assert back == msg

    import jax.numpy as jnp

    update = ModelUpdate({"w": jnp.arange(6.0).reshape(2, 3)}, ["n1", "n2"], 42)
    env = WeightsEnvelope("src:1", 2, "add_model", update)
    back = decode_weights(encode_weights(env))
    assert back.source == "src:1" and back.round == 2 and back.cmd == "add_model"
    assert back.update.contributors == ["n1", "n2"]
    assert back.update.num_samples == 42
    assert back.update.params is None and back.update.encoded


def test_grpc_connect_disconnect():
    n1, n2 = _grpc_node(), _grpc_node()
    assert n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)
    n1.disconnect(n2.addr)
    time.sleep(0.3)
    assert len(n2.get_neighbors(only_direct=True)) == 0
    n1.stop()
    n2.stop()


def test_grpc_invalid_address():
    n1 = _grpc_node()
    assert not n1.connect("127.0.0.1:1")  # nothing listens there
    n1.stop()


def test_grpc_discovery_via_beats():
    """Line topology: ends discover each other as non-direct neighbors."""
    nodes = [_grpc_node() for _ in range(3)]
    nodes[0].connect(nodes[1].addr)
    nodes[1].connect(nodes[2].addr)
    wait_convergence(nodes, 2, only_direct=False, wait=6)
    assert len(nodes[0].get_neighbors(only_direct=True)) == 1
    for n in nodes:
        n.stop()


def test_grpc_learning_end_to_end():
    """Full federated round over real sockets with wire-encoded weights."""
    full = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    nodes = []
    for i in range(2):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 2), batch_size=64)
        nodes.append(_grpc_node(learner=learner))
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=0)
    wait_to_finish(nodes, timeout=90)
    check_equal_models(nodes)
    for n in nodes:
        n.stop()


def test_grpc_int8_wire_compression_end_to_end():
    """A federation with WIRE_COMPRESSION=int8 over real sockets: payloads
    ~4x smaller, nodes still converge to (near-)equal models."""
    from p2pfl_tpu.settings import Settings

    full = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    learners = [
        JaxLearner(mlp(seed=i), full.partition(i, 2), batch_size=64) for i in range(2)
    ]
    # payload-size check on the exact tensors that would cross the wire
    params = learners[0].get_parameters()
    raw = len(encode_params(params, compression="none"))
    compressed = len(encode_params(params, compression="int8"))
    assert compressed < raw / 3.5  # fp32 -> int8 + headers/scales

    Settings.WIRE_COMPRESSION = "int8"
    try:
        nodes = [_grpc_node(learner=ln) for ln in learners]
        nodes[0].connect(nodes[1].addr)
        wait_convergence(nodes, 1, only_direct=True)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=90)
        # int8 re-quantization per hop costs precision: models equal within
        # quantization tolerance, and the aggregate still classifies
        check_equal_models(nodes, atol=0.1)
        acc = nodes[0].learner.evaluate()["test_acc"]
        assert acc > 0.5
    finally:
        Settings.WIRE_COMPRESSION = "none"
        for n in nodes:
            n.stop()


@pytest.mark.slow
@pytest.mark.parametrize("repeat", [1, 2])
def test_grpc_soak_eight_nodes_five_rounds(repeat):
    """Soak (VERDICT r2 #5): 8 nodes × 5 rounds × 1 epoch over REAL
    loopback sockets. Asserts the federation stays healthy end to end:
    every node finishes all 5 rounds, no neighbor was evicted (no
    heartbeat stall, no send-failure eviction), models are equal, and the
    federation MEAN accuracy clearly improves (deflaked assertion style —
    federation-level learning, not per-node perfection).

    Runs twice back-to-back (parametrized) — round-3 verdict weak #5: a
    soak that only passes on an idle machine proves nothing. The second
    iteration runs with deliberate background CPU load (numpy matmul
    threads, which release the GIL and genuinely compete on the 1-core
    host) so the no-eviction claim is tested under contention, not just
    in-process warmth."""
    import threading

    from p2pfl_tpu.settings import Settings

    stop_load = threading.Event()
    hogs = []
    if repeat == 2:
        def _hog():
            a = np.random.default_rng(0).standard_normal((384, 384)).astype(np.float32)
            while not stop_load.is_set():
                # GIL-free CPU pressure; renormalize so values never overflow
                a = a @ a
                a /= max(np.abs(a).max(), np.float32(1.0))

        hogs = [threading.Thread(target=_hog, daemon=True) for _ in range(2)]
        for h in hogs:
            h.start()

    full = FederatedDataset.synthetic_mnist(n_train=8 * 512, n_test=1024)
    nodes = []
    # EVERY failure-detection knob the no-eviction assertion depends on
    # must scale with the load the soak creates: on the 1-core host, eight
    # nodes' jitted fit/eval starve sender threads well past
    # set_test_settings()'s 0.5s GRPC_TIMEOUT, and a single missed
    # 1.5s-heartbeat window evicts a healthy neighbor (round-3 verdict:
    # the soak failed under load on exactly that). These are
    # failure-DETECTION latencies, not steady-state cost — widening them
    # does not mask a real stall (the wait_to_finish deadline still binds).
    old = (
        Settings.AGGREGATION_TIMEOUT, Settings.VOTE_TIMEOUT,
        Settings.GRPC_TIMEOUT, Settings.HEARTBEAT_PERIOD,
        Settings.HEARTBEAT_TIMEOUT,
    )
    Settings.AGGREGATION_TIMEOUT = 60.0
    Settings.VOTE_TIMEOUT = 30.0
    Settings.GRPC_TIMEOUT = 8.0  # a send is only "failed" past real stall territory
    Settings.HEARTBEAT_PERIOD = 1.0
    Settings.HEARTBEAT_TIMEOUT = 30.0  # ~30 missed beats, not one busy tick
    try:
        for i in range(8):
            learner = JaxLearner(
                mlp(seed=i), full.partition(i, 8), batch_size=64
            )
            nodes.append(_grpc_node(learner=learner))
        for n in nodes:
            for peer in nodes:
                if peer is not n:
                    n.connect(peer.addr)
        wait_convergence(nodes, 7, only_direct=True)
        before = float(
            sum(n.learner.evaluate()["test_acc"] for n in nodes) / len(nodes)
        )
        nodes[0].set_start_learning(rounds=5, epochs=1)
        wait_to_finish(nodes, timeout=600)
        # no stalls: every node completed the full experiment
        for n in nodes:
            assert n.state.round is None, f"{n.addr} stuck at round {n.state.round}"
        # no evictions: the full mesh survived 5 rounds of load
        for n in nodes:
            neis = n.get_neighbors(only_direct=True)
            assert len(neis) == 7, f"{n.addr} lost neighbors: has {len(neis)}"
        check_equal_models(nodes)
        after = float(
            sum(n.learner.evaluate()["test_acc"] for n in nodes) / len(nodes)
        )
        assert after > max(0.85, before + 0.2), (before, after)
    finally:
        stop_load.set()
        for h in hogs:
            h.join(timeout=5)
        (
            Settings.AGGREGATION_TIMEOUT, Settings.VOTE_TIMEOUT,
            Settings.GRPC_TIMEOUT, Settings.HEARTBEAT_PERIOD,
            Settings.HEARTBEAT_TIMEOUT,
        ) = old
        for n in nodes:
            n.stop()


@pytest.mark.slow
def test_two_process_grpc_demo():
    """examples/node1.py + node2.py: two OS processes, real loopback sockets
    (the reference's node1/node2 demo, ``p2pfl/examples/node1.py``)."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    # the image's sitecustomize claims the real TPU chip in EVERY python
    # process when PALLAS_AXON_POOL_IPS is set; two children fighting over
    # the one chip abort with a C++ exception — scrub it so they run CPU-only
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p1 = subprocess.Popen(
        [sys.executable, "-m", "p2pfl_tpu.examples.node1", str(port), "--n_train", "512"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        for _ in range(50):  # skip warnings until node1 reports listening
            line = p1.stdout.readline()
            if "listening" in line:
                break
        else:
            raise AssertionError("node1 never reported listening")
        p2 = subprocess.run(
            [
                sys.executable, "-m", "p2pfl_tpu.examples.node2", str(port),
                "--rounds", "1", "--n_train", "512",
            ],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert p2.returncode == 0, p2.stderr[-2000:]
        assert "done:" in p2.stdout and "test_acc" in p2.stdout
    finally:
        p1.kill()


def test_grpc_wire_weights_are_encoded():
    """In gRPC mode updates must cross as bytes, not live pytrees."""
    n1, n2 = _grpc_node(learner=DummyLearner()), _grpc_node(learner=DummyLearner())
    n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)

    seen = {}

    class Probe:
        @staticmethod
        def get_name():
            return "probe_weights"

        def execute(self, source, round, *args, update=None, **kwargs):  # noqa: A002
            seen["params"] = update.params
            seen["encoded"] = update.encoded

    n2.protocol.add_command(Probe())
    env = n1.protocol.build_weights("probe_weights", 0, n1.learner.get_model_update())
    assert n1.protocol.send(n2.addr, env)
    assert seen["params"] is None and seen["encoded"]
    n1.stop()
    n2.stop()


def test_grpc_corrupted_weights_stop_node_cleanly():
    """A garbage weights payload over real sockets must trip the decode
    error path (reference parity: decode errors stop the node,
    ``add_model_command.py:96-104``) — and never hang or crash the peer."""
    full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    victim = _grpc_node(learner=JaxLearner(mlp(), full.partition(0, 2), batch_size=64))
    attacker = _grpc_node(learner=JaxLearner(mlp(seed=1), full.partition(1, 2), batch_size=64))
    attacker.connect(victim.addr)
    wait_convergence([victim, attacker], 1, only_direct=True)

    # victim initiates, so it is model-initialized and collecting at once;
    # fire the garbage immediately so it lands mid-round
    victim.set_start_learning(rounds=1, epochs=1)
    garbage = ModelUpdate(None, [attacker.addr], 10, encoded=b"NOT A WEIGHTS PAYLOAD")
    env = WeightsEnvelope(attacker.addr, 0, "add_model", garbage, "corrupt-1")
    assert encode_weights(env)  # the envelope itself encodes fine
    attacker.protocol._send_to_neighbor(victim.addr, env)

    # the victim detects the decode error and stops itself (reference
    # behavior); the attacker stays healthy
    deadline = time.time() + 10
    while victim._running and time.time() < deadline:
        time.sleep(0.1)
    assert not victim._running
    assert attacker._running
    attacker.stop()
    victim.stop()  # idempotent
