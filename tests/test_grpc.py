"""gRPC transport tests: real sockets on loopback, OS-assigned ports —
the reference's own multi-node test mechanism (SURVEY §4)."""

import time

import pytest

from p2pfl_tpu.communication.grpc_transport import (
    GrpcProtocol,
    decode_message,
    decode_weights,
    encode_message,
    encode_weights,
)
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import DummyLearner, JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import wait_convergence, wait_to_finish, check_equal_models


def _grpc_node(**kwargs) -> Node:
    node = Node(protocol=GrpcProtocol("127.0.0.1:0"), **kwargs)
    node.start()
    return node


def test_codec_roundtrip():
    msg = Message("1.2.3.4:5", "vote_train_set", ("a", "1", "b", "2"), round=3, ttl=7)
    back = decode_message(encode_message(msg))
    assert back == msg

    import jax.numpy as jnp

    update = ModelUpdate({"w": jnp.arange(6.0).reshape(2, 3)}, ["n1", "n2"], 42)
    env = WeightsEnvelope("src:1", 2, "add_model", update)
    back = decode_weights(encode_weights(env))
    assert back.source == "src:1" and back.round == 2 and back.cmd == "add_model"
    assert back.update.contributors == ["n1", "n2"]
    assert back.update.num_samples == 42
    assert back.update.params is None and back.update.encoded


def test_grpc_connect_disconnect():
    n1, n2 = _grpc_node(), _grpc_node()
    assert n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)
    n1.disconnect(n2.addr)
    time.sleep(0.3)
    assert len(n2.get_neighbors(only_direct=True)) == 0
    n1.stop()
    n2.stop()


def test_grpc_invalid_address():
    n1 = _grpc_node()
    assert not n1.connect("127.0.0.1:1")  # nothing listens there
    n1.stop()


def test_grpc_discovery_via_beats():
    """Line topology: ends discover each other as non-direct neighbors."""
    nodes = [_grpc_node() for _ in range(3)]
    nodes[0].connect(nodes[1].addr)
    nodes[1].connect(nodes[2].addr)
    wait_convergence(nodes, 2, only_direct=False, wait=6)
    assert len(nodes[0].get_neighbors(only_direct=True)) == 1
    for n in nodes:
        n.stop()


def test_grpc_learning_end_to_end():
    """Full federated round over real sockets with wire-encoded weights."""
    full = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    nodes = []
    for i in range(2):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 2), batch_size=64)
        nodes.append(_grpc_node(learner=learner))
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=0)
    wait_to_finish(nodes, timeout=90)
    check_equal_models(nodes)
    for n in nodes:
        n.stop()


def test_grpc_wire_weights_are_encoded():
    """In gRPC mode updates must cross as bytes, not live pytrees."""
    n1, n2 = _grpc_node(learner=DummyLearner()), _grpc_node(learner=DummyLearner())
    n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)

    seen = {}

    class Probe:
        @staticmethod
        def get_name():
            return "probe_weights"

        def execute(self, source, round, *args, update=None, **kwargs):  # noqa: A002
            seen["params"] = update.params
            seen["encoded"] = update.encoded

    n2.protocol.add_command(Probe())
    env = n1.protocol.build_weights("probe_weights", 0, n1.learner.get_model_update())
    assert n1.protocol.send(n2.addr, env)
    assert seen["params"] is None and seen["encoded"]
    n1.stop()
    n2.stop()
