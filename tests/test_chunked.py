"""ChunkedFederation: time-shared node streaming (VERDICT r3 #3).

The class exists so v4-128-sized federations (config 3's 64 ResNet-50
nodes) EXECUTE on one chip. These tests pin its round semantics against
SpmdFederation on small models where both fit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import ChunkedFederation, SpmdFederation


def _data(n_train=256, seed=5):
    return FederatedDataset.synthetic_mnist(n_train=n_train, n_test=64, seed=seed)


def _max_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_single_chunk_matches_spmd_federation():
    """chunk_size == n, keep_opt_state=False: identical round semantics to
    SpmdFederation (same perms come from the same seeded rng calls)."""
    data = _data()
    kw = dict(n_nodes=4, batch_size=16, vote=False, seed=7)
    spmd = SpmdFederation.from_dataset(mlp(seed=0), data, **kw)
    chunked = ChunkedFederation.from_dataset(mlp(seed=0), data, chunk_size=4, **kw)
    for _ in range(2):
        spmd.run_round(epochs=1)
        chunked.run_round(epochs=1)
    assert _max_diff(spmd.node_params(0), chunked.params) < 2e-2  # bf16-scale tolerance
    sa = spmd.evaluate()["test_acc"]
    ca = chunked.evaluate()["test_acc"]
    assert abs(sa - ca) < 0.05


def test_chunking_is_invariant_to_chunk_size():
    """Streaming in chunks of 2 gives the same aggregate as one chunk of 4
    (FedAvg is a weighted sum — associative across chunks)."""
    data = _data()
    kw = dict(n_nodes=4, batch_size=16, vote=False, seed=3)
    one = ChunkedFederation.from_dataset(mlp(seed=0), data, chunk_size=4, **kw)
    two = ChunkedFederation.from_dataset(mlp(seed=0), data, chunk_size=2, **kw)
    for _ in range(2):
        one.run_round(epochs=1)
        two.run_round(epochs=1)
    assert _max_diff(one.params, two.params) < 2e-2


def test_mask_skips_chunks_and_excludes_contribution():
    """A dropped node contributes nothing; a fully-masked chunk is skipped
    (no dispatch) and the aggregate comes from the surviving chunk."""
    data = _data()
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=False, seed=3
    )
    ref = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=False, seed=3
    )
    # drop the whole second chunk in fed; ref trains only nodes 0-1 too by
    # masking — but uses a DIFFERENT chunk split so the weighted result
    # must still match
    fed.drop_node(2)
    fed.drop_node(3)
    ref.chunk_size = 4
    ref.drop_node(2)
    ref.drop_node(3)
    fed.run_round(epochs=1)
    ref.run_round(epochs=1)
    assert _max_diff(fed.params, ref.params) < 2e-2


def test_keep_opt_state_moment_averaging_trains():
    """The documented divergence: aggregated Adam moments + surviving
    schedule step counts still train (loss decreases over rounds), and the
    optimizer state's integer count leaves advance."""
    data = _data(n_train=512)
    sched = optax.warmup_cosine_decay_schedule(0.0, 3e-3, 8, 64, end_value=1e-4)
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=False,
        seed=3, tx=optax.adam(sched), keep_opt_state=True,
    )
    losses = [fed.run_round(epochs=1)["train_loss"] for _ in range(4)]
    assert losses[-1] < losses[0]
    counts = [
        int(leaf)
        for leaf in jax.tree.leaves(fed.opt_state)
        if jnp.issubdtype(leaf.dtype, jnp.integer) and leaf.ndim == 0
    ]
    assert counts and all(c == 4 * fed._nb for c in counts)
    assert fed.evaluate()["test_acc"] > 0.5


def test_vote_and_round_flops():
    data = _data()
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=True, seed=3
    )
    fed.run_round(epochs=1)
    assert fed.train_mask.sum() >= 1
    fl = fed.round_flops()
    assert fl is None or fl > 0


def test_rejects_indivisible_chunks():
    data = _data()
    with pytest.raises(ValueError, match="not divisible"):
        ChunkedFederation.from_dataset(
            mlp(seed=0), data, chunk_size=3, n_nodes=4, batch_size=16
        )
