"""ChunkedFederation: time-shared node streaming (VERDICT r3 #3).

The class exists so v4-128-sized federations (config 3's 64 ResNet-50
nodes) EXECUTE on one chip. These tests pin its round semantics against
SpmdFederation on small models where both fit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import ChunkedFederation, SpmdFederation
from p2pfl_tpu.settings import Settings


@pytest.fixture(autouse=True)
def _restore_round_knobs():
    yield
    Settings.CHUNK_STAGING_DEPTH = 2
    Settings.CHUNK_FUSED_REDUCE = True
    Settings.CHUNK_DONATE_BUFFERS = True


def _data(n_train=256, seed=5):
    return FederatedDataset.synthetic_mnist(n_train=n_train, n_test=64, seed=seed)


def _max_diff(a, b):
    return max(
        float(jnp.max(jnp.abs(x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


def test_single_chunk_matches_spmd_federation():
    """chunk_size == n, keep_opt_state=False: identical round semantics to
    SpmdFederation (same perms come from the same seeded rng calls)."""
    data = _data()
    kw = dict(n_nodes=4, batch_size=16, vote=False, seed=7)
    spmd = SpmdFederation.from_dataset(mlp(seed=0), data, **kw)
    chunked = ChunkedFederation.from_dataset(mlp(seed=0), data, chunk_size=4, **kw)
    for _ in range(2):
        spmd.run_round(epochs=1)
        chunked.run_round(epochs=1)
    assert _max_diff(spmd.node_params(0), chunked.params) < 2e-2  # bf16-scale tolerance
    sa = spmd.evaluate()["test_acc"]
    ca = chunked.evaluate()["test_acc"]
    assert abs(sa - ca) < 0.05


def test_chunking_is_invariant_to_chunk_size():
    """Streaming in chunks of 2 gives the same aggregate as one chunk of 4
    (FedAvg is a weighted sum — associative across chunks)."""
    data = _data()
    kw = dict(n_nodes=4, batch_size=16, vote=False, seed=3)
    one = ChunkedFederation.from_dataset(mlp(seed=0), data, chunk_size=4, **kw)
    two = ChunkedFederation.from_dataset(mlp(seed=0), data, chunk_size=2, **kw)
    for _ in range(2):
        one.run_round(epochs=1)
        two.run_round(epochs=1)
    assert _max_diff(one.params, two.params) < 2e-2


def test_mask_skips_chunks_and_excludes_contribution():
    """A dropped node contributes nothing; a fully-masked chunk is skipped
    (no dispatch) and the aggregate comes from the surviving chunk."""
    data = _data()
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=False, seed=3
    )
    ref = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=False, seed=3
    )
    # drop the whole second chunk in fed; ref trains only nodes 0-1 too by
    # masking — but uses a DIFFERENT chunk split so the weighted result
    # must still match
    fed.drop_node(2)
    fed.drop_node(3)
    ref.chunk_size = 4
    ref.drop_node(2)
    ref.drop_node(3)
    fed.run_round(epochs=1)
    ref.run_round(epochs=1)
    assert _max_diff(fed.params, ref.params) < 2e-2


def test_keep_opt_state_moment_averaging_trains():
    """The documented divergence: aggregated Adam moments + surviving
    schedule step counts still train (loss decreases over rounds), and the
    optimizer state's integer count leaves advance."""
    data = _data(n_train=512)
    sched = optax.warmup_cosine_decay_schedule(0.0, 3e-3, 8, 64, end_value=1e-4)
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=False,
        seed=3, tx=optax.adam(sched), keep_opt_state=True,
    )
    losses = [fed.run_round(epochs=1)["train_loss"] for _ in range(4)]
    assert losses[-1] < losses[0]
    counts = [
        int(leaf)
        for leaf in jax.tree.leaves(fed.opt_state)
        if jnp.issubdtype(leaf.dtype, jnp.integer) and leaf.ndim == 0
    ]
    assert counts and all(c == 4 * fed._nb for c in counts)
    assert fed.evaluate()["test_acc"] > 0.5


def test_vote_and_round_flops():
    data = _data()
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), data, chunk_size=2, n_nodes=4, batch_size=16, vote=True, seed=3
    )
    fed.run_round(epochs=1)
    assert fed.train_mask.sum() >= 1
    fl = fed.round_flops()
    assert fl is None or fl > 0


def _run_with_knobs(fused, depth, donate=True, resident=True, keep=False, rounds=2):
    Settings.CHUNK_FUSED_REDUCE = fused
    Settings.CHUNK_STAGING_DEPTH = depth
    Settings.CHUNK_DONATE_BUFFERS = donate
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), _data(), chunk_size=2, n_nodes=4, batch_size=16, vote=False,
        seed=3, resident=resident, keep_opt_state=keep,
    )
    entries = [fed.run_round(epochs=1) for _ in range(rounds)]
    return fed, entries


def test_overlapped_path_matches_serial_path():
    """The overhaul's correctness contract (ISSUE 3): the overlapped path
    (fused on-device accumulators, donated buffers, staged-ahead inputs)
    must match the serial reference path (host-side reduce, depth-1
    staging). The accumulation ORDER is identical by construction (fp32
    zero-init + in-program adds ≡ the host's first-chunk-then-add chain);
    the tolerance below covers one-ulp XLA fusion differences in the
    chunk program's weighted tensordot, measured ≤1e-9 over 2 rounds."""
    fast, ef = _run_with_knobs(fused=True, depth=2)
    ref, er = _run_with_knobs(fused=False, depth=1)
    assert _max_diff(fast.params, ref.params) < 1e-7
    # the on-device loss/weight accumulation is exactly the serial chain
    assert ef[-1]["train_loss"] == er[-1]["train_loss"]


def test_overlap_knobs_do_not_change_results():
    """Donation, staging depth, and non-resident streaming are pure
    execution strategies — bit-identical results."""
    base, _ = _run_with_knobs(fused=True, depth=2)
    for kw in ({"donate": False}, {"depth": 1}, {"depth": 4}, {"resident": False}):
        other, _ = _run_with_knobs(fused=True, **{"depth": 2, **kw})
        assert _max_diff(base.params, other.params) == 0.0, kw


def test_overlapped_keep_opt_state_matches_serial():
    """Aggregated-moment path through the donated accumulators: the fused
    finalize divides the SAME weighted opt sums the host path builds."""
    fast, _ = _run_with_knobs(fused=True, depth=2, keep=True)
    ref, _ = _run_with_knobs(fused=False, depth=1, keep=True)
    assert _max_diff(fast.opt_state, ref.opt_state) < 1e-7
    # integer schedule-step leaves advance identically
    def counts(tree):
        return [
            int(x)
            for x in jax.tree.leaves(tree)
            if jnp.issubdtype(x.dtype, jnp.integer) and x.ndim == 0
        ]

    assert counts(fast.opt_state) == counts(ref.opt_state)


def test_nonresident_streaming_masks_and_flops():
    """resident=False streams x/y chunks from host RAM through the staging
    pipeline: dropped nodes and round_flops must behave as in resident mode."""
    Settings.CHUNK_STAGING_DEPTH = 3
    fed = ChunkedFederation.from_dataset(
        mlp(seed=0), _data(), chunk_size=2, n_nodes=4, batch_size=16, vote=False,
        seed=3, resident=False,
    )
    assert fed.x_chunks is None and len(fed._x_np) == 2
    fed.drop_node(2)
    fed.drop_node(3)
    fed.run_round(epochs=1)
    assert fed.round == 1
    fl = fed.round_flops()
    assert fl is None or fl > 0
    assert fed.evaluate()["test_acc"] >= 0.0


def test_rejects_indivisible_chunks():
    data = _data()
    with pytest.raises(ValueError, match="not divisible"):
        ChunkedFederation.from_dataset(
            mlp(seed=0), data, chunk_size=3, n_nodes=4, batch_size=16
        )
