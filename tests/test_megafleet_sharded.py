"""Sharded megafleet engine: bit-identity to the single-device chunked
engine, plus the chunk autotune cache contract.

The sharded engine's ONLY claim is layout, not semantics: client
parameter rows move to per-shard blocks (plus one local trash row each)
and each chunk's trained rows come back through one tiled ``all_gather``
— a pure concatenation, so no float op reassociates and every verdict,
counter and loss must be BITWISE equal to the single-device chunked
engine on the same spec. These tests pin that across device counts,
topologies, the fault algebra, and both chunk layouts (aligned reshape
and the greedy fallback).

``tests/conftest.py`` forces ``--xla_force_host_platform_device_count=8``
so 1/2/4/8-shard meshes always exist here; the guard skips anyway so the
file stays runnable under a bare interpreter.
"""

from __future__ import annotations

import json

import jax
import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    ByzantineSpec,
    FaultPlan,
    JoinSpec,
    LeaveSpec,
)
from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet
from p2pfl_tpu.ops import fleet_autotune as ft
from p2pfl_tpu.settings import Settings

SEED = 1234


def _need(n_shards: int) -> None:
    if jax.device_count() < n_shards:  # pragma: no cover — conftest gives 8
        pytest.skip(f"needs {n_shards} devices, have {jax.device_count()}")


def _run(n, *, shards=None, chunk=48, cluster_size=0, plan=None, **kw):
    spec = FleetSpec.synth(n, seed=SEED, dim=6)
    return MegaFleet(
        spec,
        k=max(4, n // 32),
        updates_per_node=3,
        chunk=chunk,
        shards=shards,
        cluster_size=cluster_size,
        plan=plan,
        **kw,
    ).run()


def _assert_bit_identical(a, b):
    """Counters EXACT, losses and final params BITWISE equal."""
    assert b.version == a.version
    assert b.merges == a.merges
    assert b.regional_merges == a.regional_merges
    assert b.stale_dropped == a.stale_dropped
    assert b.rate_limited == a.rate_limited
    assert b.byz_corrupted == a.byz_corrupted
    assert b.staleness_hist_global == a.staleness_hist_global
    la = np.asarray([l for _, _, l in a.loss_curve])
    lb = np.asarray([l for _, _, l in b.loss_curve])
    assert np.array_equal(la, lb), f"loss diverges by {np.abs(la - lb).max()}"
    assert np.array_equal(a.params["w"], b.params["w"])


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
@pytest.mark.parametrize("cluster_size", [0, 64], ids=["flat", "hier"])
def test_sharded_bit_identical_1k(n_shards, cluster_size):
    _need(n_shards)
    base = _run(1000, cluster_size=cluster_size)
    got = _run(1000, shards=n_shards, cluster_size=cluster_size)
    _assert_bit_identical(base, got)


@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_bit_identical_byzantine(n_shards):
    # sign_flip + scale + noise attackers: corruption counts and the
    # corrected-adopter writeback (the one sharded scatter beyond pass A)
    # must match the chunked engine exactly
    _need(n_shards)
    plan = FaultPlan(
        seed=3,
        byzantine={
            "sim-0002": ByzantineSpec(kind="sign_flip"),
            "sim-0010": ByzantineSpec(kind="scale", lam=4.0),
            "sim-0020": ByzantineSpec(kind="noise", noise_std=0.5),
        },
    )
    base = _run(600, plan=plan)
    assert base.byz_corrupted > 0
    _assert_bit_identical(base, _run(600, shards=n_shards, plan=plan))


@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_bit_identical_churn(n_shards):
    _need(n_shards)
    plan = FaultPlan(
        seed=3,
        joins={"sim-0599": JoinSpec(at_s=2.0)},
        leaves={"sim-0005": LeaveSpec(at_s=1.5)},
    )
    base = _run(600, plan=plan)
    got = _run(600, shards=n_shards, plan=plan)
    _assert_bit_identical(base, got)
    assert got.joined == base.joined and got.left == base.left


def test_sharded_greedy_fallback_layout():
    # tiny fleet + many updates: clients repeat inside a chunk, so the
    # aligned-reshape fast path is rejected and the greedy segment
    # layout must produce the same verdicts
    _need(4)
    base = _run(40, chunk=48)
    _assert_bit_identical(base, _run(40, chunk=48, shards=4))


@pytest.mark.slow
@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_bit_identical_10k(n_shards):
    _need(n_shards)
    base = _run(10_000, chunk=128)
    _assert_bit_identical(base, _run(10_000, chunk=128, shards=n_shards))


# ---- autotune cache contract ----


def test_autotune_cache_roundtrip(tmp_path):
    # chunk=0 measures once, persists, and a fresh in-process state
    # replays the SAME chunk from disk with no re-measure
    Settings.FLEET_TUNE_CACHE = str(tmp_path / "tune.json")
    ft.clear_memory_cache()
    spec = FleetSpec.synth(400, seed=2, dim=4)
    m = MegaFleet(spec, k=16, updates_per_node=3, chunk=0, shards=2)
    assert m._chunk_auto
    r1 = m.run()
    raw = json.loads((tmp_path / "tune.json").read_text())
    [(key, entry)] = raw.items()
    assert key.startswith("cpu|shards=2|")
    assert entry["chunk"] == m.chunk
    assert set(entry["timings"]) == {str(c) for c in ft.DEFAULT_CANDIDATES}

    ft.clear_memory_cache()  # forget the measurement, keep the disk file
    calls = []
    orig = ft.autotune_fleet_chunk

    def spy(measure, *a, **kw):
        def counting(c):
            calls.append(c)
            return measure(c)

        return orig(counting, *a, **kw)

    ft_autotune, ft.autotune_fleet_chunk = ft.autotune_fleet_chunk, spy
    try:
        m2 = MegaFleet(spec, k=16, updates_per_node=3, chunk=0, shards=2)
        r2 = m2.run()
    finally:
        ft.autotune_fleet_chunk = ft_autotune
    assert calls == []  # replayed from disk: zero engine measurements
    assert m2.chunk == m.chunk
    _assert_bit_identical(r1, r2)
    ft.clear_memory_cache()


def test_autotune_pin_wins_and_is_not_persisted(tmp_path):
    Settings.FLEET_TUNE_CACHE = str(tmp_path / "tune.json")
    ft.clear_memory_cache()
    ft.pin_fleet_chunk(96, n_shards=1, extra="x")
    assert ft.get_fleet_chunk(n_shards=1, extra="x") == 96
    got = ft.autotune_fleet_chunk(lambda c: 1.0, n_shards=1, extra="x")
    assert got == 96  # pin wins, measure never ran
    assert not (tmp_path / "tune.json").exists()  # pins are session-only
    ft.clear_memory_cache()
    assert ft.get_fleet_chunk(n_shards=1, extra="x") is None


def test_mesh_helpers_validate():
    from p2pfl_tpu.parallel.fleet_mesh import fleet_clients_mesh, shard_capacity

    assert shard_capacity(1000, 4) == 250
    assert shard_capacity(1001, 4) == 251
    with pytest.raises(ValueError):
        shard_capacity(0, 4)
    with pytest.raises(ValueError, match="exceeds"):
        fleet_clients_mesh(jax.device_count() + 1)
    mesh = fleet_clients_mesh(2)
    assert mesh.axis_names == (Settings.MESH_CLIENTS_AXIS,)
    assert mesh.size == 2
