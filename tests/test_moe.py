"""Mixture-of-experts FFN + expert parallelism.

The reference has no MoE (its models are MLP/CNN, SURVEY §2.7); this covers
the expert-parallel axis of the multi-chip design: capacity-based dense
dispatch (`models/transformer.py:MoEMLP`), aux-loss plumbing
(`models/base.py:apply_with_aux`), and the EP sharding rules
(`parallel/sharding.py`).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from p2pfl_tpu.models.transformer import MoEMLP, TransformerConfig, tiny_transformer


def _moe_cfg(**kw):
    base = dict(
        vocab_size=64,
        dim=32,
        n_layers=2,
        n_heads=2,
        n_kv_heads=2,
        ffn_hidden=64,
        n_experts=4,
        moe_top_k=2,
        lora_rank=0,
    )
    base.update(kw)
    return TransformerConfig(**base)


@pytest.mark.slow
def test_moe_forward_shape_and_aux():
    m = tiny_transformer(seq_len=16, cfg=_moe_cfg())
    x = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    logits, aux = m.apply_with_aux(m.params, x)
    assert logits.shape == (4, 16, 64)
    # balance loss is ~1 at uniform routing; scaled by the 1e-2 coefficient
    assert 0.0 < float(aux) < 1.0
    # plain apply (no mutable) also works and matches
    np.testing.assert_allclose(np.asarray(m.apply(m.params, x)), np.asarray(logits))


@pytest.mark.slow
def test_dense_model_aux_is_zero():
    m = tiny_transformer(seq_len=16, cfg=_moe_cfg(n_experts=0))
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    _, aux = m.apply_with_aux(m.params, x)
    assert float(aux) == 0.0


@pytest.mark.slow
def test_moe_single_expert_is_plain_swiglu():
    """E=1, k=1, ample capacity: routing is the identity, so the layer must
    equal the SwiGLU computed directly from the (single) expert's weights."""
    cfg = _moe_cfg(n_experts=1, moe_top_k=1, moe_capacity=2.0)
    layer = MoEMLP(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, cfg.dim), jnp.float32)
    variables = layer.init(jax.random.PRNGKey(1), x)
    out = layer.apply(variables, x)

    p = variables["params"]
    dt = cfg.dtype
    xs = x.reshape(-1, cfg.dim).astype(dt)
    h = jax.nn.silu(xs @ p["w1"][0].astype(dt)) * (xs @ p["w3"][0].astype(dt))
    ref = (h @ p["w2"][0].astype(dt)).reshape(x.shape)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2, rtol=2e-2
    )


@pytest.mark.slow
def test_moe_router_learns_and_loss_decreases():
    m = tiny_transformer(seq_len=16, cfg=_moe_cfg())
    x = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, 64)
    y = jnp.roll(x, -1, axis=1)

    def loss(p):
        logits, aux = m.apply_with_aux(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean() + aux

    tx = optax.adam(1e-2)
    params = m.params
    opt = tx.init(params)

    @jax.jit
    def step(p, o):
        l, g = jax.value_and_grad(loss)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, l, g

    params, opt, l0, g0 = step(params, opt)
    router_grads = [
        v
        for kp, v in jax.tree_util.tree_leaves_with_path(g0)
        if "router" in "/".join(str(getattr(q, "key", q)) for q in kp)
    ]
    assert router_grads and all(float(jnp.abs(v).max()) > 0 for v in router_grads)
    for _ in range(15):
        params, opt, l, _ = step(params, opt)
    assert float(l) < float(l0)


@pytest.mark.slow
def test_moe_tight_capacity_still_runs():
    """Over-capacity tokens are dropped (ride the residual), never crash."""
    cfg = _moe_cfg(moe_capacity=0.25, moe_top_k=1)
    m = tiny_transformer(seq_len=16, cfg=cfg)
    x = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0, 64)
    logits, aux = m.apply_with_aux(m.params, x)
    assert bool(jnp.isfinite(logits).all()) and bool(jnp.isfinite(aux))


@pytest.mark.slow
def test_moe_expert_parallel_matches_replicated():
    """Grads with the expert axis sharded over 8 devices == unsharded grads."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from p2pfl_tpu.parallel import federation_mesh
    from p2pfl_tpu.parallel.sharding import transformer_shardings

    # f32 end to end: in bf16 the sharded matmuls' different reduction order
    # perturbs activations enough to flip near-tie argmax routing decisions,
    # which changes outputs materially — a property of MoE, not a bug.
    cfg = _moe_cfg(n_experts=8, moe_top_k=2, dtype=jnp.float32)
    m = tiny_transformer(seq_len=16, cfg=cfg)
    x = jax.random.randint(jax.random.PRNGKey(4), (8, 16), 0, 64)
    y = jnp.roll(x, -1, axis=1)

    def loss(p):
        logits, aux = m.apply_with_aux(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean() + aux

    g_ref = jax.grad(loss)(m.params)

    mesh = federation_mesh(model_parallel=8)
    shardings = transformer_shardings(mesh, m.params)
    # the EP rule must actually shard the expert stacks over the model axis
    specs = {
        "/".join(str(getattr(q, "key", q)) for q in kp): s.spec
        for kp, s in jax.tree_util.tree_leaves_with_path(shardings)
    }
    assert specs["layer_0/mlp/w1"] == P("model", None, None)
    assert specs["layer_0/mlp/router"] == P()

    p_sharded = jax.device_put(m.params, shardings)
    g_sh = jax.jit(jax.grad(loss), out_shardings=shardings)(p_sharded)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_sh)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-5, rtol=1e-4
        )


@pytest.mark.slow
def test_moe_learner_fit():
    """JaxLearner trains an MoE LM end to end (aux loss included in the step)."""
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner

    m = tiny_transformer(seq_len=16, cfg=_moe_cfg())
    data = FederatedDataset.synthetic_lm(vocab_size=64, seq_len=16, n_train=64, n_test=16)
    learner = JaxLearner(m, data, "moe-test", epochs=1, batch_size=8)
    learner.fit()
    metrics = learner.evaluate()
    assert np.isfinite(metrics["test_loss"])


def test_moe_remat_policy_grads_match_full_remat():
    """The selective-remat tags in MoEMLP (expert gate/up hiddens share the
    dense MLP's tag names) change only what the backward saves: grads under
    remat_policy='mlp'/'mlp_qkv' must equal blanket per-block remat."""
    import optax

    from p2pfl_tpu.models.base import apply_with_aux

    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    results = {}
    for pol in (None, "mlp", "mlp_qkv"):
        # f32: at bf16 the SAVED hidden is rounded to storage precision
        # while the blanket-remat recompute stays in f32 registers through
        # fusion — a ~1e-3 rounding delta that is not a math difference
        # (verified: f32 grads match exactly)
        cfg = TransformerConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_hidden=64, lora_rank=0, n_experts=2, moe_top_k=1,
            remat=True, remat_policy=pol, dtype=jnp.float32,
        )
        m = tiny_transformer(seq_len=16, seed=0, cfg=cfg)

        def loss(p, m=m):
            logits, aux = apply_with_aux(m.module, p, toks)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.roll(toks, -1, 1)
            ).mean()
            return ce + aux

        results[pol] = jax.jit(jax.value_and_grad(loss))(m.params)
    l0, g0 = results[None]
    for pol in ("mlp", "mlp_qkv"):
        l, g = results[pol]
        assert abs(float(l) - float(l0)) < 1e-6
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g0)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
