"""Ring-flash attention: Pallas flash blocks inside the ppermute ring
(`ops/attention.py:_ring_flash_sharded` + the offset-aware kernels in
`ops/flash_attention.py`). O(T_local·D) memory per device per hop instead
of the dense ring body's O(T_local²) logits. The reference has no
attention at all (SURVEY §2.9)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from p2pfl_tpu.ops.attention import causal_attention, ring_attention
from p2pfl_tpu.ops.flash_attention import flash_attention_block
from p2pfl_tpu.parallel import federation_mesh


def _qkv(t=64, b=2, h=2, d=16, seed=0):
    key = jax.random.PRNGKey(seed)
    return tuple(jax.random.normal(s, (b, t, h, d), jnp.float32) for s in jax.random.split(key, 3))


@pytest.mark.slow
def test_block_offsets_cover_visibility_cases():
    """Diagonal (causal), fully-visible, and fully-masked offset blocks."""
    from p2pfl_tpu.ops.flash_attention import FlashConfig

    cfg8 = FlashConfig(block_q=8, block_k=8)
    q, k, v = _qkv(t=16)
    # diagonal: q_off == k_off => plain causal over the block
    out, lse = flash_attention_block(q, k, v, 0, 0, cfg8, True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(causal_attention(q, k, v)), atol=2e-5, rtol=1e-4
    )
    # fully visible: q rows all AFTER k cols => no masking anywhere
    out_full, lse_full = flash_attention_block(q, k, v, 100, 0, cfg8, True)
    assert bool(jnp.isfinite(out_full).all()) and bool(jnp.isfinite(lse_full).all())
    # fully masked: k cols all after q rows => zero output, -inf lse
    out_none, lse_none = flash_attention_block(q, k, v, 0, 100, cfg8, True)
    np.testing.assert_allclose(np.asarray(out_none), 0.0)
    assert bool((lse_none < -1e29).all())


@pytest.mark.parametrize("n_dev", [2, 4, 8])
@pytest.mark.slow
def test_ring_flash_matches_dense(n_dev):
    mesh = federation_mesh(model_parallel=n_dev)
    q, k, v = _qkv(t=64)
    ref = causal_attention(q, k, v)
    out = ring_attention(q, k, v, mesh, "model", impl="flash", block=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5, rtol=1e-4)


@pytest.mark.slow
def test_ring_flash_grads_match_dense():
    mesh = federation_mesh(model_parallel=4)
    q, k, v = _qkv(t=64, seed=3)

    def loss(args):
        return jnp.sum(ring_attention(*args, mesh, "model", impl="flash", block=8) ** 2)

    def loss_ref(args):
        return jnp.sum(causal_attention(*args) ** 2)

    g = jax.grad(loss)((q, k, v))
    gr = jax.grad(loss_ref)((q, k, v))
    for a, b, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-3, err_msg=f"d{name}"
        )


def test_ring_flash_rejects_non_causal():
    mesh = federation_mesh(model_parallel=2)
    q, k, v = _qkv(t=32)
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, mesh, "model", causal=False, impl="flash")


@pytest.mark.slow
def test_transformer_trains_with_ring_flash():
    """attn='ring_flash' end to end: grads through the pipeline of embed →
    blocks(ring-flash attention) → head match the dense-attention model."""
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    mesh = federation_mesh(model_parallel=4)
    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_hidden=64, lora_rank=0, dtype=jnp.float32,
    )
    m_ring = tiny_transformer(seq_len=32, cfg=cfg, attn="ring_flash", mesh=mesh)
    m_dense = tiny_transformer(seq_len=32, cfg=cfg)  # same seed => same params
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, 64)
    targets = jnp.roll(tokens, -1, axis=1)

    def loss(m):
        def f(p):
            logits = m.apply(p, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()
        return f

    np.testing.assert_allclose(
        np.asarray(m_ring.apply(m_ring.params, tokens)),
        np.asarray(m_dense.apply(m_dense.params, tokens)),
        atol=1e-4, rtol=1e-3,
    )
    g = jax.grad(loss(m_ring))(m_ring.params)
    gr = jax.grad(loss(m_dense))(m_dense.params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-3)
