"""Stall watchdog: stack dumps when a learning round stops moving."""

import time

import pytest

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.watchdog import StallWatchdog, all_thread_stacks
from p2pfl_tpu.node_state import NodeState
from p2pfl_tpu.settings import Settings


@pytest.fixture(autouse=True)
def _clean():
    yield
    StallWatchdog.shutdown()
    Settings.STALL_WATCHDOG_S = 0.0
    logger.unregister_node("stuck-node")
    logger.unregister_node("moving-node")


def test_all_thread_stacks_names_threads():
    dump = all_thread_stacks()
    assert "MainThread" in dump and "test_all_thread_stacks" in dump


def test_disabled_by_default():
    assert Settings.STALL_WATCHDOG_S == 0.0
    assert StallWatchdog.ensure_started() is None


def test_stall_detected_and_reported_once():
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = _Capture()
    logging.getLogger("p2pfl_tpu").addHandler(handler)  # propagate=False: attach directly
    Settings.STALL_WATCHDOG_S = 0.4

    stuck = NodeState("stuck-node")
    stuck.status = "Learning"
    stuck.round = 1
    stuck.current_stage = "VoteTrainSetStage"
    stuck.last_transition = time.monotonic() - 10.0
    logger.register_node("stuck-node", stuck)

    moving = NodeState("moving-node")
    moving.status = "Learning"
    moving.last_transition = time.monotonic()
    logger.register_node("moving-node", moving)

    assert StallWatchdog.ensure_started() is not None

    def wait_for_hits(expected, timeout=5.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            moving.last_transition = time.monotonic()  # it really does move
            got = [r for r in records if "STALL" in r.getMessage()]
            if len(got) >= expected:
                return got
            time.sleep(0.1)
        return [r for r in records if "STALL" in r.getMessage()]

    hits = wait_for_hits(1)
    assert hits, "watchdog never reported the stall"
    msg = hits[0].getMessage()
    assert "stuck-node" in msg and "VoteTrainSetStage" in msg
    assert "stall-watchdog" in msg or "MainThread" in msg  # stacks included
    assert all("moving-node" not in r.getMessage() for r in hits)
    # the stall is also a countable health metric (chaos tests / CI assert
    # zero stalls via get_comm_metrics instead of grepping logs)
    assert logger.get_comm_metrics("stuck-node").get("stall_detected", 0) == 1
    assert logger.get_comm_metrics("moving-node").get("stall_detected", 0) == 0

    # one report per stall, not one per tick
    hits2 = wait_for_hits(2, timeout=1.0)
    assert len(hits2) == len(hits)

    # a transition clears the report latch; a NEW stall reports again
    stuck.last_transition = time.monotonic() - 10.0
    hits3 = wait_for_hits(len(hits) + 1, timeout=2.0)
    assert len(hits3) == len(hits) + 1
    logging.getLogger("p2pfl_tpu").removeHandler(handler)
