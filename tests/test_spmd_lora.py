"""SPMD LoRA federation + TP sharding rules tests."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
from p2pfl_tpu.parallel import SpmdLoraFederation

CFG = TransformerConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_hidden=128)


def _data():
    return FederatedDataset.synthetic_lm(vocab_size=CFG.vocab_size, seq_len=32, n_train=512, n_test=64)


@pytest.mark.slow
def test_spmd_lora_learns_and_diffuses():
    # wider adapters + higher lr: the frozen base is random (not pretrained),
    # so the adapters carry all the learning in this test
    cfg = TransformerConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=128, lora_rank=16, lora_mlp=True,
    )
    model = tiny_transformer(seq_len=32, cfg=cfg)
    fed = SpmdLoraFederation.from_dataset(
        model, _data(), n_nodes=4, batch_size=8, vote=False, learning_rate=1e-2
    )
    before = fed.evaluate()["test_acc"]
    fed.run(rounds=4, epochs=1)
    after = fed.evaluate()["test_acc"]
    assert after > max(before, 0.1)
    # all nodes hold the same adapters after diffusion
    a = jax.tree.leaves(fed.node_params(0))
    b = jax.tree.leaves(fed.node_params(3))
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x, np.float32), np.asarray(y, np.float32), atol=1e-6)


def test_spmd_lora_state_is_adapters_only():
    model = tiny_transformer(seq_len=32, cfg=CFG)
    fed = SpmdLoraFederation.from_dataset(model, _data(), n_nodes=4, batch_size=8, vote=False)
    stacked = sum(x.size for x in jax.tree.leaves(fed.params))
    base = sum(x.size for x in jax.tree.leaves(fed.base))
    full = sum(x.size for x in jax.tree.leaves(model.params))
    assert stacked == 4 * (full - base)  # adapters only, stacked N times
    assert stacked < base  # federation state is smaller than one base model


@pytest.mark.slow
def test_tp_sharding_rules():
    from p2pfl_tpu.parallel.mesh import federation_mesh
    from p2pfl_tpu.parallel.sharding import partition_spec_for, transformer_shardings
    from jax.sharding import PartitionSpec as P

    assert partition_spec_for("layer_0/attn/wq/kernel") == P(None, "model")
    assert partition_spec_for("layer_0/attn/wo/kernel") == P("model", None)
    assert partition_spec_for("layer_1/mlp/w2/kernel") == P("model", None)
    assert partition_spec_for("layer_0/attn/wq/lora_a") == P()
    assert partition_spec_for("final_norm/scale") == P()

    mesh = federation_mesh(model_parallel=4, devices=jax.devices()[:4])
    model = tiny_transformer(seq_len=16, cfg=CFG)
    shardings = transformer_shardings(mesh, model.params)
    wq = shardings["layer_0"]["attn"]["wq"]["kernel"]
    assert wq.spec == P(None, "model")


def test_tp_sharded_forward_matches_replicated():
    """Forward pass with TP-sharded base == replicated base."""
    from p2pfl_tpu.parallel.mesh import federation_mesh
    from p2pfl_tpu.parallel.sharding import shard_transformer

    mesh = federation_mesh(model_parallel=4, devices=jax.devices()[:4])
    model = tiny_transformer(seq_len=16, cfg=CFG)
    toks = jnp.arange(16, dtype=jnp.int32)[None] % CFG.vocab_size
    want = model.apply(model.params, toks)
    sharded = shard_transformer(mesh, model.params)
    got = jax.jit(lambda p, t: model.module.apply({"params": p}, t))(sharded, toks)
    # bf16 matmuls accumulate in a different order when sharded
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-2)


@pytest.mark.slow
def test_lora_fused_matches_sequential():
    """run_fused(R) must produce the same adapters as R run_round calls
    with the same seed (one dispatch vs R dispatches)."""
    import numpy as np

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLoraFederation

    cfg = TransformerConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2, ffn_hidden=64)
    data = FederatedDataset.synthetic_lm(vocab_size=64, seq_len=16, n_train=4 * 32, n_test=16)

    def build():
        return SpmdLoraFederation.from_dataset(
            tiny_transformer(seq_len=16, cfg=cfg), data, n_nodes=4,
            batch_size=8, vote=False, seed=5,
        )

    seq = build()
    for _ in range(3):
        seq.run_round(epochs=1)
    fused = build()
    fused.run_fused(3, epochs=1)

    for a, b in zip(jax.tree.leaves(seq.params), jax.tree.leaves(fused.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert fused.round == 3


def test_node_chunk_matches_unchunked():
    """``node_chunk`` reorders the node axis from one vmap into a scan of
    vmapped chunks — identical round results, and a non-dividing chunk
    size is rejected."""
    import numpy as np

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLoraFederation

    data = FederatedDataset.synthetic_lm(
        vocab_size=64, seq_len=16, n_train=32, n_test=16
    )

    def make(nc):
        cfg = TransformerConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_hidden=64, lora_rank=2, remat=True, scan_layers=True,
        )
        m = tiny_transformer(seq_len=16, seed=0, cfg=cfg)
        return SpmdLoraFederation.from_dataset(
            m, data, n_nodes=4, batch_size=4, vote=False, seed=3, node_chunk=nc
        )

    a, b = make(0), make(2)
    ea, eb = a.run_round(epochs=1), b.run_round(epochs=1)
    assert float(ea["train_loss"]) == pytest.approx(float(eb["train_loss"]), abs=1e-6)
    for x, y in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    bad = make(3)
    with pytest.raises(ValueError, match="node_chunk"):
        bad.run_round(epochs=1)
