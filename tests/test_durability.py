"""Crash-resurrection (ISSUE 20): durable node journals, deterministic
restart/rejoin.

Five layers, mirroring the change's structure:

- the journal frame + manifest codec: roundtrip fidelity, retention GC,
  and the SeqCounter the async context's streams now run on;
- crash consistency under torture: ≥50 random mid-write kills (torn
  temp files, torn final frames, kills between the frame commit and the
  manifest commit, torn manifests) — recovery always lands on a
  committed snapshot, never a torn one — plus the hostile-corruption
  fixture exercising the CRC checks both ways;
- the simulator under RestartSpec: bit-exact replay from ``(seed,
  plan)``, crash-and-restart recovering the update budget a crash-only
  plan loses, and the death-epoch guard on both sides of the eviction
  window;
- the sequence-resumption regression over REAL gRPC: a resumed node's
  first push is accepted (never ``async_dup_drop``ped — the journaled
  seq + margin outruns every upstream VersionVector mark), while a
  pre-crash in-flight duplicate of its last update IS dropped;
- the live drill: a member of an in-process fleet is hard-crashed
  mid-round by a FaultPlan RestartSpec and resumed from its journal by
  the ``resurrect_fn`` seam — survivors and resurrectee converge on one
  global.
"""

import json
import os
import random
import re
import time

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    CrashSpec,
    FaultPlan,
    RestartSpec,
    hard_crash,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.federation.durability import (
    BufferJournal,
    JournalSnapshot,
    NodeJournal,
    SeqCounter,
    rebuild_updates,
)
from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    yield
    Settings.FEDERATION_MODE = "sync"
    Settings.HIER_CLUSTER_SIZE = 0
    MemoryRegistry.reset()


def _sum_metric(metric):
    return sum(d.get(metric, 0.0) for d in logger.get_comm_metrics().values())


def _pace(seconds):
    """A stage hook that paces local updates so faults land mid-run."""

    def hook(node, stage_name):
        if stage_name == "AsyncTrainStage":
            time.sleep(seconds)

    return hook


def _mk_snap(addr: str, marker: int) -> JournalSnapshot:
    """A snapshot whose every integrity-checkable field encodes ``marker``."""
    return JournalSnapshot(
        addr=addr,
        xid="xp-dur",
        members=[addr, "peer-a", "peer-b"],
        dead=["peer-b"],
        global_version=marker,
        base_version=max(marker - 1, 0),
        high_water=marker,
        train_seq=marker + 1,
        up_seq=marker,
        total_rounds=10,
        updates_done=marker,
        suspicion={"peer-a": 0.25},
        quarantined=[],
        global_params={"w": np.full(16, float(marker), np.float32)},
        buffers=[
            BufferJournal(
                tier="regional",
                version=marker,
                vv={"peer-a": marker},
                pending=[
                    (
                        "peer-a",
                        marker,
                        max(marker - 1, 0),
                        ["peer-a"],
                        3,
                        {"w": np.full(16, float(marker) * 2.0, np.float32)},
                    )
                ],
            )
        ],
    )


def _flat_array(params):
    """The single tensor of a template-less recovered params dict."""
    assert len(params) == 1
    return np.asarray(next(iter(params.values())))


# ---------------------------------------------------------------------------
# codec + retention
# ---------------------------------------------------------------------------


def test_seq_counter_is_journalable_count():
    c = SeqCounter(5)
    assert c.next_value == 5
    assert next(c) == 5 and next(c) == 6
    assert c.next_value == 7  # never issued yet


def test_journal_roundtrip_and_retention(tmp_path):
    j = NodeJournal(str(tmp_path), node_name="rt", keep_n=3)
    for marker in range(1, 6):
        j.commit_snapshot(_mk_snap("rt", marker))
    # retention: only the newest keep_n frames survive GC
    frames = sorted(p.name for p in tmp_path.glob("snap-*.p2pj"))
    assert frames == ["snap-3.p2pj", "snap-4.p2pj", "snap-5.p2pj"]
    rec = NodeJournal(str(tmp_path)).recover()
    assert rec is not None and rec.snap == 5
    assert rec.addr == "rt" and rec.xid == "xp-dur"
    assert rec.members == ["peer-a", "peer-b", "rt"] or rec.members == [
        "rt",
        "peer-a",
        "peer-b",
    ]
    assert rec.dead == ["peer-b"]
    assert rec.global_version == 5 and rec.train_seq == 6 and rec.high_water == 5
    assert rec.suspicion == {"peer-a": 0.25}
    np.testing.assert_array_equal(_flat_array(rec.global_params), np.full(16, 5.0))
    (bj,) = rec.buffers
    assert bj.tier == "regional" and bj.version == 5 and bj.vv == {"peer-a": 5}
    ups = rebuild_updates(bj, rec.xid)
    assert len(ups) == 1
    assert ups[0].version == ("peer-a", 5, 4) and ups[0].xp == "xp-dur"
    assert ups[0].contributors == ["peer-a"] and ups[0].num_samples == 3
    # a new journal over the same directory numbers past the survivors
    assert NodeJournal(str(tmp_path))._next_snap == 6


def test_journal_recover_with_template_rebuilds_pytrees(tmp_path):
    j = NodeJournal(str(tmp_path), node_name="tp")
    j.commit_snapshot(_mk_snap("tp", 4))
    template = {"w": np.zeros(16, np.float32)}
    rec = NodeJournal(str(tmp_path)).recover(template=template)
    assert set(rec.global_params.keys()) == {"w"}
    np.testing.assert_array_equal(np.asarray(rec.global_params["w"]), np.full(16, 4.0))
    np.testing.assert_array_equal(
        np.asarray(rec.buffers[0].pending[0][5]["w"]), np.full(16, 8.0)
    )


def test_journal_empty_directory_recovers_none(tmp_path):
    assert NodeJournal(str(tmp_path)).recover() is None
    with pytest.raises(FileNotFoundError):
        Node.resume(str(tmp_path), learner=DummyLearner(value=0.0), start=False)


# ---------------------------------------------------------------------------
# crash consistency: torture + hostile corruption
# ---------------------------------------------------------------------------


class _Killed(Exception):
    """The injected SIGKILL: aborts a commit at a chosen byte offset."""


class _KillableJournal(NodeJournal):
    """A journal whose writes can be killed mid-flight, byte-exactly.

    ``kill_mode`` selects where the next commit dies; ``record`` tracks
    ground truth (which frames are durable, which snapshot the manifest
    last committed) so the test can state the recovery invariant.
    """

    kill_mode = None
    rng = None
    record = None
    current_marker = 0

    def _write_atomic(self, name, payload):
        is_manifest = name == "MANIFEST"
        mode = self.kill_mode
        if mode == "frame_tmp" and not is_manifest:
            # killed mid temp-file write: torn bytes at the TEMP name,
            # final name never appears
            cut = self.rng.randrange(0, len(payload))
            with open(os.path.join(self.directory, f"{name}.tmp.kill"), "wb") as f:
                f.write(payload[:cut])
            raise _Killed(name)
        if mode == "frame_torn" and not is_manifest:
            # the adversarial case the trailing CRC exists for: torn
            # bytes surface at the FINAL name (power loss reordering)
            cut = self.rng.randrange(0, len(payload))
            with open(os.path.join(self.directory, name), "wb") as f:
                f.write(payload[:cut])
            raise _Killed(name)
        if mode == "pre_manifest" and is_manifest:
            # killed between the frame commit and the manifest commit
            raise _Killed(name)
        if mode == "manifest_torn" and is_manifest:
            cut = self.rng.randrange(0, len(payload))
            with open(os.path.join(self.directory, name), "wb") as f:
                f.write(payload[:cut])
            raise _Killed(name)
        super()._write_atomic(name, payload)
        if is_manifest:
            self.record["floor"] = int(json.loads(payload)["snap"])
        else:
            m = re.match(r"^snap-(\d+)\.p2pj$", name)
            if m:
                self.record["durable"][int(m.group(1))] = self.current_marker


def test_journal_torture_random_midwrite_kills(tmp_path):
    """≥50 random mid-write kills: recovery ALWAYS lands on a committed
    (or at worst durable-but-uncommitted, never torn) snapshot whose
    content verifies bit-exactly against what was written."""
    rng = random.Random(20)
    record = {"durable": {}, "floor": 0}

    def fresh_journal():
        j = _KillableJournal(str(tmp_path), node_name="tort", keep_n=0)
        j.rng = rng
        j.record = record
        return j

    j = fresh_journal()
    kills = 0
    marker = 0
    modes = ["frame_tmp", "frame_torn", "pre_manifest", "manifest_torn"]
    while kills < 55:
        marker += 1
        mode = rng.choice(modes + [None, None])  # ~1/3 clean commits
        j.kill_mode = mode
        j.current_marker = marker
        if mode is None:
            j.commit_snapshot(_mk_snap("tort", marker))
            continue
        with pytest.raises(_Killed):
            j.commit_snapshot(_mk_snap("tort", marker))
        kills += 1
        # "reboot": a fresh journal over the directory, as resume() does
        j = fresh_journal()
        j.kill_mode = None
        rec = j.recover()
        assert rec is not None, "a kill destroyed the committed snapshot"
        # the recovery invariant: a durable frame, never behind the
        # manifest's committed floor, content bit-exact as written
        assert rec.snap in record["durable"], f"recovered torn frame {rec.snap}"
        assert rec.snap >= record["floor"]
        want = record["durable"][rec.snap]
        assert rec.global_version == want
        np.testing.assert_array_equal(
            _flat_array(rec.global_params), np.full(16, float(want))
        )
        (bj,) = rec.buffers
        assert bj.vv == {"peer-a": want}
    assert kills >= 50 and record["floor"] > 0


def test_journal_corruption_fixture_both_ways(tmp_path):
    """The CRC checks cross-verify: a corrupt manifest falls back to the
    newest self-verifying frame; a corrupt frame fails the manifest's CRC
    AND its own, falling back to the previous committed snapshot."""
    j = NodeJournal(str(tmp_path), node_name="fx", keep_n=0)
    for marker in (1, 2, 3):
        j.commit_snapshot(_mk_snap("fx", marker))
    manifest = tmp_path / "MANIFEST"
    committed = manifest.read_bytes()
    # (a) manifest corrupted → scan finds the newest frame by its own CRC
    manifest.write_bytes(b'{"snapshot": "snap-3.p2pj", "crc": 1}')
    rec = NodeJournal(str(tmp_path)).recover()
    assert rec is not None and rec.snap == 3 and rec.global_version == 3
    manifest.write_bytes(b"\x00garbage\xff")
    rec = NodeJournal(str(tmp_path)).recover()
    assert rec is not None and rec.snap == 3 and rec.global_version == 3
    # (b) manifest intact but its frame torn → double fallback to snap-2
    manifest.write_bytes(committed)
    frame = tmp_path / "snap-3.p2pj"
    payload = bytearray(frame.read_bytes())
    payload[len(payload) // 2] ^= 0xFF
    frame.write_bytes(bytes(payload))
    rec = NodeJournal(str(tmp_path)).recover()
    assert rec is not None and rec.snap == 2 and rec.global_version == 2
    np.testing.assert_array_equal(_flat_array(rec.global_params), np.full(16, 2.0))
    # (b') truncation instead of a bit flip — same outcome
    frame.write_bytes(frame.read_bytes()[: len(payload) // 3])
    rec = NodeJournal(str(tmp_path)).recover()
    assert rec is not None and rec.snap == 2


# ---------------------------------------------------------------------------
# simulator: RestartSpec replay + recovery
# ---------------------------------------------------------------------------


def _addrs(n):
    return [f"sim-{i:04d}" for i in range(n)]


def _restart_plan(n, resume_after=2.0, victims=(3, 11, 27)):
    addrs = _addrs(n)
    return FaultPlan(
        seed=1905,
        restarts={
            addrs[i]: RestartSpec(round_no=1, resume_after_s=resume_after)
            for i in victims
        },
    )


def test_simfleet_restart_replays_bit_exact_and_recovers_budget():
    """ISSUE 20 acceptance (sim half): crash-and-restart replays
    bit-exact from (seed, plan) and recovers the update budget a
    crash-only plan permanently loses."""
    n, victims = 40, (3, 11, 27)

    def run(plan):
        return SimulatedAsyncFleet(
            n, seed=11, cluster_size=8, updates_per_node=5, plan=plan,
            evict_delay=0.5,
        ).run()

    a, b = run(_restart_plan(n)), run(_restart_plan(n))
    assert sorted(a.restarted) == [f"sim-{i:04d}" for i in sorted(victims)]
    assert a.restarted == b.restarted  # event-time order, deterministic
    assert a.crashed == b.crashed and sorted(a.crashed) == sorted(a.restarted)
    assert a.version == b.version and a.version > 0
    np.testing.assert_array_equal(np.asarray(a.params["w"]), np.asarray(b.params["w"]))
    assert a.loss_curve == b.loss_curve
    # restart recovers the budget: every node finishes all its updates,
    # while crash-only forfeits the victims' remainders
    c = run(
        FaultPlan(
            seed=1905,
            crashes={
                _addrs(n)[i]: CrashSpec("AsyncTrainStage", round_no=1)
                for i in victims
            },
        )
    )
    assert not c.restarted
    assert a.updates_sent == n * 5
    assert c.updates_sent < a.updates_sent
    # minted versions stay strictly monotone through death AND rebirth
    versions = [v for _t, v, _l in a.loss_curve]
    assert versions == sorted(versions) and len(set(versions)) == len(versions)


def test_simfleet_restart_epoch_guard_on_both_sides_of_eviction():
    """A resurrection BEFORE the eviction window must invalidate the
    pending evict (the death-epoch guard); one AFTER it re-derives the
    node back in. Both replay bit-exact."""
    n = 24

    def run(resume_after):
        return SimulatedAsyncFleet(
            n, seed=5, cluster_size=8, updates_per_node=4,
            plan=_restart_plan(n, resume_after=resume_after, victims=(7,)),
            evict_delay=1.0,
        ).run()

    # resume at 0.2 < evict_delay 1.0: the corpse returns before the
    # survivors ever noticed — the stale evict must not fire later
    fast_a, fast_b = run(0.2), run(0.2)
    assert fast_a.restarted == ["sim-0007"]
    assert fast_a.loss_curve == fast_b.loss_curve
    np.testing.assert_array_equal(
        np.asarray(fast_a.params["w"]), np.asarray(fast_b.params["w"])
    )
    # resume at 3.0 > evict_delay: evicted, then re-derived back in
    slow_a, slow_b = run(3.0), run(3.0)
    assert slow_a.restarted == ["sim-0007"]
    assert slow_a.loss_curve == slow_b.loss_curve
    np.testing.assert_array_equal(
        np.asarray(slow_a.params["w"]), np.asarray(slow_b.params["w"])
    )
    # both worlds complete the victim's budget
    assert fast_a.updates_sent == n * 4 and slow_a.updates_sent == n * 4


# ---------------------------------------------------------------------------
# real gRPC: the sequence-resumption regression
# ---------------------------------------------------------------------------


def test_grpc_resume_first_push_accepted_precrash_duplicate_dropped(tmp_path):
    """ISSUE 20 regression over REAL sockets: after resurrection the
    node's first pushes are accepted (journaled seq + margin outruns the
    aggregator's VersionVector marks), while a pre-crash in-flight
    duplicate of its LAST update — finally delivered — is deduped, not
    double-merged."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 2
    Settings.HIER_CLUSTER_SIZE = 0
    jdir = str(tmp_path / "journal")
    nodes = [
        Node(learner=DummyLearner(value=float(i)), protocol=GrpcProtocol("127.0.0.1:0"))
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True, wait=10)
    by_addr = sorted(n.addr for n in nodes)
    root = next(n for n in nodes if n.addr == by_addr[0])  # the aggregator
    victim = next(n for n in nodes if n.addr == by_addr[-1])  # an edge
    victim.enable_journal(jdir)
    for n in nodes:
        n.stage_hooks.append(_pace(0.35))
    revived = None
    try:
        root.set_start_learning(rounds=8, epochs=1)
        deadline = time.monotonic() + 25
        while _sum_metric("journal_snapshot") < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert _sum_metric("journal_snapshot") >= 2, "victim never snapshotted"
        hard_crash(victim)
        # peek the journal for the pre-crash identity (as resume() will)
        peek = NodeJournal(jdir).recover()
        last_seq = peek.train_seq - 1
        assert last_seq >= 1
        dup_base = _sum_metric("async_dup_drop")
        revived = Node.resume(
            jdir, learner=DummyLearner(value=0.0), protocol=GrpcProtocol, rounds=3
        )
        assert revived.addr == victim.addr  # same identity, same port
        # replay the pre-crash in-flight duplicate over the wire: the
        # root's VersionVector already holds this (origin, seq) mark
        dup = ModelUpdate(
            {k: np.zeros_like(np.asarray(v)) for k, v in revived.learner.get_parameters().items()},
            [victim.addr],
            1,
        )
        dup.version = (victim.addr, last_seq, peek.base_version)
        dup.xp = peek.xid
        env = revived.protocol.build_weights("async_update", 0, dup)
        assert revived.protocol.send(root.addr, env, create_connection=True)
        deadline = time.monotonic() + 10
        while _sum_metric("async_dup_drop") < dup_base + 1 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert _sum_metric("async_dup_drop") == dup_base + 1, "duplicate not deduped"
        survivors = [n for n in nodes if n is not victim] + [revived]
        wait_to_finish(survivors, timeout=60)
        assert _sum_metric("node_resumed") == 1
        # the ONLY drop is the forged duplicate: every organic post-resume
        # push from the revived node was accepted (seq margin held)
        assert _sum_metric("async_dup_drop") == dup_base + 1
        assert _sum_metric("async_merge") >= 2
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        targets = nodes + ([revived] if revived is not None else [])
        for n in targets:
            n.stop()


# ---------------------------------------------------------------------------
# live drill: FaultPlan RestartSpec + resurrect_fn
# ---------------------------------------------------------------------------


def test_live_kill_and_resurrect_drill(tmp_path):
    """ISSUE 20 acceptance (live half): a 5-node fleet, one member
    hard-crashed mid-round by a RestartSpec and resumed from its journal
    through the resurrect_fn seam — it rejoins via the elastic path and
    the whole fleet (survivors + resurrectee) converges on one global."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 2
    Settings.HIER_CLUSTER_SIZE = 0
    jdir = str(tmp_path / "journal")
    nodes = [Node(learner=DummyLearner(value=float(i)), address=f"rz-{i}") for i in range(5)]
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 4, only_direct=True, wait=10)
    victim = nodes[3]  # an edge (rz-0 sorts first → aggregates)
    victim.enable_journal(jdir)
    revived_box = []

    def resurrect(addr):
        assert addr == victim.addr
        revived_box.append(
            Node.resume(jdir, learner=DummyLearner(value=0.0), rounds=2)
        )

    plan = FaultPlan(
        seed=7,
        restarts={victim.addr: RestartSpec(round_no=2, resume_after_s=1.0)},
    )
    install_fault_plan(nodes, plan, resurrect_fn=resurrect)
    for n in nodes:
        n.stage_hooks.append(_pace(0.35))
    try:
        nodes[0].set_start_learning(rounds=6, epochs=1)
        deadline = time.monotonic() + 30
        while not revived_box and time.monotonic() < deadline:
            time.sleep(0.1)
        assert revived_box, "the resurrection timer never fired"
        survivors = [n for n in nodes if n is not victim] + revived_box
        wait_to_finish(survivors, timeout=60)
        assert _sum_metric("fault_crash") >= 1
        assert _sum_metric("node_resumed") == 1
        assert _sum_metric("journal_recovered") == 1
        assert _sum_metric("journal_restored") == 1
        assert _sum_metric("async_merge") >= 2
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        remove_fault_plan(nodes)
        for n in nodes + revived_box:
            n.stop()
