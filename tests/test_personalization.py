"""FedPer personalization: federated body, node-local head."""

import jax
import numpy as np
import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.exceptions import ModelNotMatchingError
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.personalization import PersonalizedLearner
from p2pfl_tpu.learning.weights import _flatten_named
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


HEAD = "Dense_2"  # the MLP's output layer


def _learner(i, n, full, **kw):
    return PersonalizedLearner(
        mlp(seed=i), full.partition(i, n), batch_size=64, personal=(HEAD,), **kw
    )


def test_update_excludes_personal_paths():
    full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    learner = _learner(0, 2, full)
    upd = learner.get_model_update()
    paths = set(_flatten_named(upd.params))
    assert paths and all(not p.startswith(HEAD) for p in paths)
    # full params DO contain the head
    assert any(p.startswith(HEAD) for p in _flatten_named(learner.params))


def test_set_parameters_preserves_head_and_checks_structure():
    full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    a, b = _learner(0, 2, full), _learner(1, 2, full)
    head_before = {
        k: np.asarray(v)
        for k, v in _flatten_named(a.params).items()
        if k.startswith(HEAD)
    }
    a.set_parameters(b.get_model_update().params)  # body-only tree
    flat = _flatten_named(a.params)
    for k, v in head_before.items():
        np.testing.assert_array_equal(np.asarray(flat[k]), v)  # head untouched
    bflat = _flatten_named(b.params)
    body_keys = [k for k in flat if not k.startswith(HEAD)]
    for k in body_keys:
        np.testing.assert_array_equal(np.asarray(flat[k]), np.asarray(bflat[k]))

    with pytest.raises(ModelNotMatchingError):
        a.set_parameters({"bogus": np.zeros((2, 2), np.float32)})


def test_bad_personal_prefixes_rejected():
    full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    with pytest.raises(ValueError, match="matches no parameters"):
        PersonalizedLearner(
            mlp(), full.partition(0, 2), batch_size=64, personal=("NoSuchLayer",)
        )
    # a TYPO'D prefix among valid ones must fail too, not silently federate
    # the layer the user marked private
    with pytest.raises(ValueError, match="Dens_1"):
        PersonalizedLearner(
            mlp(), full.partition(0, 2), batch_size=64, personal=(HEAD, "Dens_1")
        )
    with pytest.raises(ValueError, match="at least one"):
        PersonalizedLearner(mlp(), full.partition(0, 2), batch_size=64, personal=())


@pytest.mark.slow
def test_personalized_federation_over_grpc():
    """Uniform personalized federation over real sockets: body-only
    payloads cross as bytes through materialize() and reconstruct against
    each receiver's body template."""
    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol

    full = FederatedDataset.synthetic_mnist(n_train=768, n_test=128)
    nodes = [
        Node(learner=_learner(i, 3, full), protocol=GrpcProtocol("127.0.0.1:0"))
        for i in range(3)
    ]
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    nodes[0].set_start_learning(rounds=3, epochs=2)
    wait_to_finish(nodes, timeout=240)
    # What this test pins down is the BYTE path (body-only payloads
    # reconstruct through materialize) — not gossip's timeout
    # nondeterminism: under the shrunken test clocks a node's final
    # aggregation may legitimately resolve to a partial (reference
    # semantics), leaving its head trained against a different body.
    # Assert the majority property instead of per-node perfection.
    accs = sorted(n.learner.evaluate()["test_acc"] for n in nodes)
    assert accs[-1] > 0.7 and accs[-2] > 0.6, accs
    for n in nodes:
        n.stop()


def test_mixed_plain_and_personalized_fails_loudly_not_hanging():
    """A plain JaxLearner mixed into a personalized federation is a
    configuration error (the plain node cannot consume body-only updates)
    — it must stop itself via the model-mismatch path, like the
    reference's wrong-model scenario (``test/node_test.py:155-176``),
    never hang the experiment."""
    import time

    from p2pfl_tpu.learning.learner import JaxLearner

    full = FederatedDataset.synthetic_mnist(n_train=512, n_test=64)
    plain = Node(learner=JaxLearner(mlp(seed=0), full.partition(0, 2), batch_size=64))
    pers = Node(learner=_learner(1, 2, full))
    plain.start(), pers.start()
    plain.connect(pers.addr)
    wait_convergence([plain, pers], 1, only_direct=True)
    pers.set_start_learning(rounds=1, epochs=1)
    deadline = time.monotonic() + 60
    while plain._running and time.monotonic() < deadline:
        time.sleep(0.2)
    assert not plain._running  # mismatch detected, node stopped itself
    plain.stop(), pers.stop()


def test_personalized_federation_end_to_end():
    """3 nodes federate bodies over gossip; heads stay distinct per node,
    bodies converge identical, and every node's model still works."""
    full = FederatedDataset.synthetic_mnist(n_train=1536, n_test=256)
    nodes = []
    for i in range(3):
        node = Node(learner=_learner(i, 3, full))
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    # heads train only locally (that's the point), so give them one more
    # round than a fully-federated run would need
    nodes[0].set_start_learning(rounds=3, epochs=2)
    wait_to_finish(nodes, timeout=180)

    flats = [_flatten_named(n.learner.params) for n in nodes]
    body_keys = [k for k in flats[0] if not k.startswith(HEAD)]
    head_keys = [k for k in flats[0] if k.startswith(HEAD)]
    assert body_keys and head_keys
    for k in body_keys:
        np.testing.assert_allclose(
            np.asarray(flats[0][k]), np.asarray(flats[1][k]), atol=1e-1
        )
    # heads trained locally from different seeds/shards — they differ
    assert any(
        not np.allclose(np.asarray(flats[0][k]), np.asarray(flats[1][k]), atol=1e-3)
        for k in head_keys
    )
    # only nodes that actually trained have fitted heads (FedPer property;
    # see the gRPC twin test)
    trained = [n for n in nodes if n.learner._steps_done > 0]
    assert len(trained) >= 2
    for n in trained:
        acc = n.learner.evaluate()["test_acc"]
        assert acc > 0.7, acc
    for n in nodes:
        n.stop()
