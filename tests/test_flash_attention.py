"""Flash-attention kernel tests (pallas interpret mode on CPU).

The kernel schedule is the static :class:`FlashConfig` — block shapes,
q ownership and backward mode all ride explicit config objects here (the
old module-global ``BWD_MODE`` is gone; see test_kernel_config.py for the
jit cache-key / staleness coverage).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.ops.attention import causal_attention
from p2pfl_tpu.ops.flash_attention import FlashConfig, flash_attention


def _qkv(b=2, t=128, h=4, d=32, seed=0, dtype=jnp.float32):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in keys)


def test_flash_matches_dense_causal():
    q, k, v = _qkv()
    want = causal_attention(q, k, v)
    got = flash_attention(q, k, v, True, FlashConfig(32, 32), True)  # interpret
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-5)


def test_flash_non_causal():
    q, k, v = _qkv(t=64)
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d**-0.5)
    p = jax.nn.softmax(s, axis=-1)
    want = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    got = flash_attention(q, k, v, False, FlashConfig(32, 32), True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-5)


def test_flash_uneven_blocks():
    """block_q != block_k and T not equal to block sizes."""
    q, k, v = _qkv(t=96)
    want = causal_attention(q, k, v)
    got = flash_attention(q, k, v, True, FlashConfig(32, 48), True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-5)


def test_flash_default_config_resolves():
    """config=None resolves through the autotune lookup chain (defaults
    table on this platform) and still matches dense."""
    q, k, v = _qkv(t=64)
    want = causal_attention(q, k, v)
    got = flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-5)


@pytest.mark.parametrize("q_span", [2, 4])
def test_flash_q_span_matches_dense(q_span):
    """Wider q ownership per program is a pure schedule change."""
    q, k, v = _qkv(t=128)
    want = causal_attention(q, k, v)
    got = flash_attention(q, k, v, True, FlashConfig(16, 32, q_span=q_span), True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-5)


@pytest.mark.slow
def test_flash_gradient_matches_dense():
    q, k, v = _qkv(b=1, t=32, h=2, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, FlashConfig(16, 16), True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_flash_in_transformer():
    """The attn="flash" selector wires the kernel into the model."""
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    cfg = TransformerConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2, ffn_hidden=64)
    m_flash = tiny_transformer(seq_len=32, cfg=cfg, attn="flash", seed=4)
    m_dense = tiny_transformer(seq_len=32, cfg=cfg, seed=4)
    toks = (jnp.arange(32, dtype=jnp.int32) % 64)[None]
    a = m_flash.apply(m_flash.params, toks)
    b = m_dense.apply(m_dense.params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-2)


def test_auto_attention_picks_by_length():
    """attn="auto" (VERDICT r2 #8): on TPU, dense below the measured
    crossover (Settings.FLASH_MIN_SEQ_LEN, from bench config 7) and flash
    at/above; on every OTHER backend always dense — interpret-mode Pallas
    is a correctness path, not a performance one."""
    from p2pfl_tpu.models.transformer import (
        TransformerConfig,
        pick_attention,
        resolve_attention,
        tiny_transformer,
    )
    from p2pfl_tpu.settings import Settings

    t = Settings.FLASH_MIN_SEQ_LEN
    assert pick_attention(t - 1, backend="tpu") == "dense"
    assert pick_attention(t, backend="tpu") == "flash"
    assert pick_attention(t * 8, backend="cpu") == "dense"  # non-TPU gate
    with pytest.raises(ValueError, match="seq_len"):
        resolve_attention("auto")
    # this suite runs on the CPU backend: auto resolves to the dense path
    # (None) at any length, and the model builds/runs
    assert resolve_attention("auto", seq_len=t * 8) is None
    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2, ffn_hidden=64
    )
    m_auto = tiny_transformer(seq_len=32, cfg=cfg, attn="auto", seed=4)
    m_dense = tiny_transformer(seq_len=32, cfg=cfg, seed=4)
    toks = (jnp.arange(32, dtype=jnp.int32) % 64)[None]
    np.testing.assert_allclose(
        np.asarray(m_auto.apply(m_auto.params, toks)),
        np.asarray(m_dense.apply(m_dense.params, toks)),
        atol=5e-2,
    )


@pytest.mark.slow
def test_flash_transformer_training_grads_match_dense():
    """Training the transformer with flash attention: full LM-loss gradients
    match the dense model's (pattern of test_ring_training.py)."""
    import optax

    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2, ffn_hidden=64,
        dtype=jnp.float32,
    )
    seq = 32
    m_flash = tiny_transformer(seq_len=seq, cfg=cfg, attn="flash", seed=9)
    m_dense = tiny_transformer(seq_len=seq, cfg=cfg, seed=9)

    def loss_fn(model):
        def loss(params, x, y):
            logits = model.module.apply({"params": params}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        return loss

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, seq)), jnp.int32)
    y = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, seq)), jnp.int32)
    g_flash = jax.grad(loss_fn(m_flash))(m_flash.params, x, y)
    g_dense = jax.grad(loss_fn(m_dense))(m_dense.params, x, y)
    for a, b in zip(jax.tree.leaves(g_flash), jax.tree.leaves(g_dense)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=5e-3
        )


def test_flash_resolver_rejects_unknown():
    from p2pfl_tpu.models.transformer import resolve_attention

    with pytest.raises(ValueError):
        resolve_attention("nope")
    with pytest.raises(ValueError):
        resolve_attention("ring")  # needs a mesh


@pytest.mark.slow
def test_bwd_specific_blocks_match_shared_blocks():
    """block_q_bwd/block_k_bwd change only the backward SCHEDULE: gradients
    must match the shared-block configuration (the saved lse's [B, H, 1, T]
    row layout is block-size independent — no relayout either way)."""
    q, k, v = _qkv(t=256, h=2)

    def loss(config):
        def f(q_, k_, v_):
            o = flash_attention(q_, k_, v_, True, config, True)
            return jnp.sum(o * o)

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_shared = loss(FlashConfig(64, 64))  # bwd uses the fwd's 64-blocks
    g_bwd128 = loss(FlashConfig(64, 64, block_q_bwd=128, block_k_bwd=128))
    for a, bb in zip(g_shared, g_bwd128):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb), atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_fused_bwd_matches_split(causal):
    """The single-pass dkvq kernel (persistent dQ scratch across k-block
    grid steps) must produce the SAME gradients as the split dq/dkv pair —
    it only removes the S/dP recompute, not any math. bwd_mode is now an
    explicit static config knob, not a module global."""
    q, k, v = _qkv(b=2, t=128, h=2, d=16)

    def grads(mode):
        def f(q_, k_, v_):
            o = flash_attention(
                q_, k_, v_, causal, FlashConfig(32, 64, bwd_mode=mode), True
            )
            return jnp.sum(o * o)

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    g_split = grads("split")
    g_fused = grads("fused")
    for a, b in zip(g_fused, g_split):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fused_bwd_matches_dense_gradient():
    q, k, v = _qkv(b=1, t=64, h=2, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, True, FlashConfig(16, 32, bwd_mode="fused"), True)
        return jnp.sum(o**2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_fused_bwd_offs_matches_split():
    """Offset-variant single-pass backward == split pair, including the
    lse cotangent path the ring merge differentiates through."""
    from p2pfl_tpu.ops import flash_attention as fa

    q, k, v = _qkv(b=1, t=64, h=2, d=16)

    def grads(q_off, k_off, mode):
        def f(q_, k_, v_):
            o, lse = fa.flash_attention_block(
                q_, k_, v_, jnp.int32(q_off), jnp.int32(k_off),
                FlashConfig(16, 32, bwd_mode=mode), True,
            )
            # touch BOTH outputs so the lse cotangent is non-trivial
            return jnp.sum(o * o) + jnp.sum(jnp.where(lse <= -5e29, 0.0, lse)) * 1e-3

        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for q_off, k_off in ((0, 0), (64, 0), (0, 64), (64, 64)):
        g_split = grads(q_off, k_off, "split")
        g_fused = grads(q_off, k_off, "fused")
        for a, b in zip(g_fused, g_split):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5,
                err_msg=f"offsets ({q_off}, {k_off})",
            )
