"""Transformer / LoRA / ring-attention tests (BASELINE config 5 family)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.lora import LoRALearner, merge_params, split_lora
from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
from p2pfl_tpu.ops.attention import causal_attention, ring_attention

CFG = TransformerConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, ffn_hidden=128)


@pytest.mark.slow
def test_ring_attention_matches_dense():
    """Ring attention over the 8-device mesh == single-device causal attention."""
    from p2pfl_tpu.parallel.mesh import federation_mesh

    mesh = federation_mesh(model_parallel=8)  # all devices on the model axis
    b, t, h, d = 2, 64, 4, 16
    rng = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(rng, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)

    dense = causal_attention(q, k, v)
    ring = ring_attention(q, k, v, mesh, axis_name="model")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring), atol=2e-5)


@pytest.mark.slow
def test_ring_attention_non_causal():
    from p2pfl_tpu.parallel.mesh import federation_mesh

    mesh = federation_mesh(model_parallel=4, devices=jax.devices()[:4])
    b, t, h, d = 1, 32, 2, 8
    rng = jax.random.PRNGKey(1)
    q, k, v = (jax.random.normal(key, (b, t, h, d)) for key in jax.random.split(rng, 3))
    # full (non-causal) attention reference
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d**-0.5)
    probs = jax.nn.softmax(logits, axis=-1)
    want = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    got = ring_attention(q, k, v, mesh, axis_name="model", causal=False)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=2e-5)


@pytest.mark.slow
def test_transformer_forward_and_lora_split():
    model = tiny_transformer(seq_len=32, cfg=CFG)
    toks = jnp.zeros((2, 32), jnp.int32)
    logits = model.apply(model.params, toks)
    assert logits.shape == (2, 32, CFG.vocab_size)

    lora, base = split_lora(model.params)
    n_lora = sum(x.size for x in jax.tree.leaves(lora))
    n_base = sum(x.size for x in jax.tree.leaves(base))
    assert 0 < n_lora < n_base * 0.2
    # merge restores the full structure
    merged = merge_params(base, lora)
    assert jax.tree.structure(merged) == jax.tree.structure(model.params)


def test_lora_zero_init_is_identity():
    """Fresh adapters (B=0) must not change the forward pass."""
    cfg_no = TransformerConfig(**{**CFG.__dict__, "lora_rank": 0})
    m_lora = tiny_transformer(seq_len=16, cfg=CFG, seed=3)
    m_none = tiny_transformer(seq_len=16, cfg=cfg_no, seed=3)
    toks = jnp.arange(16, dtype=jnp.int32)[None]
    a = m_lora.apply(m_lora.params, toks)
    b = m_none.apply(m_none.params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_lora_learner_trains_and_freezes_base():
    data = FederatedDataset.synthetic_lm(vocab_size=CFG.vocab_size, seq_len=32, n_train=64, n_test=16)
    model = tiny_transformer(seq_len=32, cfg=CFG)
    learner = LoRALearner(model, data, batch_size=8)
    base_before = jax.tree.leaves(learner.base)
    lora_before = [np.asarray(x).copy() for x in jax.tree.leaves(learner.lora)]
    learner.fit()
    # base unchanged, adapters moved
    for a, b in zip(base_before, jax.tree.leaves(learner.base)):
        assert a is b
    moved = any(
        not np.allclose(a, np.asarray(b)) for a, b in zip(lora_before, jax.tree.leaves(learner.lora))
    )
    assert moved
    metrics = learner.evaluate()
    assert "test_acc" in metrics


def test_federated_lora_over_memory_transport():
    """Two nodes exchange ONLY adapter subtrees and converge to equal LoRA."""
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils import wait_convergence, wait_to_finish, check_equal_models

    MemoryRegistry.reset()
    data = FederatedDataset.synthetic_lm(vocab_size=CFG.vocab_size, seq_len=32, n_train=128, n_test=16)
    nodes = []
    for i in range(2):
        model = tiny_transformer(seq_len=32, cfg=CFG, seed=0)
        learner = LoRALearner(model, data.partition(i, 2), batch_size=8)
        nodes.append(Node(learner=learner))
    for n in nodes:
        n.start()
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes, atol=1e-4)  # compares exchanged (LoRA) params
    for n in nodes:
        n.stop()
    MemoryRegistry.reset()


def test_scan_layers_matches_unrolled():
    """cfg.scan_layers stacks params on a leading [L] axis and must compute
    the SAME function as the unrolled model (copy unrolled layer params into
    the stacked layout and compare logits); remat composes with the scan and
    LoRA grads flow."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from p2pfl_tpu.learning.lora import merge_params, split_lora
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    kw = dict(
        vocab_size=128, dim=32, n_layers=3, n_heads=2, n_kv_heads=1,
        ffn_hidden=48, lora_rank=4, dtype=jnp.float32,
    )
    mu = tiny_transformer(seq_len=16, cfg=TransformerConfig(**kw))
    ms = tiny_transformer(
        seq_len=16, cfg=TransformerConfig(**kw, scan_layers=True, remat=True)
    )
    assert set(ms.params) == {"embed", "final_norm", "layers"}
    stacked = jax.tree.map(
        lambda *xs: jnp.stack(xs), *[mu.params[f"layer_{i}"] for i in range(3)]
    )
    ps = {"embed": mu.params["embed"], "final_norm": mu.params["final_norm"],
          "layers": {"block": stacked}}
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    lu = mu.module.apply({"params": mu.params}, tok)
    ls = ms.module.apply({"params": ps}, tok)
    np.testing.assert_allclose(np.asarray(lu), np.asarray(ls), atol=1e-5)

    lora, base = split_lora(ps)
    assert jax.tree.leaves(lora), "stacked layout must still expose lora_* leaves"

    def loss(lo):
        p = merge_params(lo, base)
        logits = ms.module.apply({"params": p}, tok)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.roll(tok, -1, 1)
        ).mean()

    g = jax.grad(loss)(lora)
    assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(g))
    assert sum(float(jnp.sum(jnp.abs(x))) for x in jax.tree.leaves(g)) > 0


def test_scan_layers_rejects_moe():
    import pytest as _pytest

    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    cfg = TransformerConfig(
        vocab_size=64, dim=16, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_hidden=32, n_experts=2, scan_layers=True,
    )
    with _pytest.raises(NotImplementedError, match="scan_layers with MoE"):
        tiny_transformer(seq_len=8, cfg=cfg)


def test_remat_policy_grads_match_full_remat():
    """Selective remat (``remat_policy``) changes WHAT the backward saves,
    never the math: loss and grads must equal the blanket-remat ones, and
    an unknown policy is rejected at trace time."""
    import jax
    import jax.numpy as jnp
    import optax
    import pytest as _pytest

    from p2pfl_tpu.models.transformer import (
        TransformerConfig,
        _remat_policy,
        tiny_transformer,
    )

    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    results = {}
    for pol in (None, "mlp", "mlp_qkv"):
        cfg = TransformerConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_hidden=64, lora_rank=2, remat=True, scan_layers=True,
            remat_policy=pol,
        )
        m = tiny_transformer(seq_len=16, seed=0, cfg=cfg)

        def loss(p, m=m):
            logits = m.apply(p, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, jnp.roll(toks, -1, 1)
            ).mean()

        results[pol] = jax.jit(jax.value_and_grad(loss))(m.params)
    l0, g0 = results[None]
    for pol in ("mlp", "mlp_qkv"):
        l, g = results[pol]
        assert float(l) == _pytest.approx(float(l0), abs=1e-6)
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g0)):
            import numpy as np

            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    with _pytest.raises(ValueError, match="remat_policy"):
        _remat_policy("bogus")
