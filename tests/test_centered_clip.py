"""Centered clipping (Karimireddy, He & Jaggi 2021) — ops, host, SPMD."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning.aggregators import CenteredClip
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.ops.aggregation import centered_clip
from p2pfl_tpu.parallel import SpmdFederation
from p2pfl_tpu.utils import check_equal_models, full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


def test_centered_clip_bounds_attacker_displacement():
    """An arbitrarily scaled outlier moves the aggregate by at most tau."""
    center = {"w": jnp.zeros((8, 4))}
    honest = {"w": jnp.full((3, 8, 4), 0.1)}
    attack = {"w": jnp.full((1, 8, 4), 1e6)}
    stacked = {"w": jnp.concatenate([honest["w"], attack["w"]])}
    out = centered_clip(stacked, center, tau=1.0, iters=3)
    # honest deviation norm ~0.57 < tau (kept whole); attacker clipped to tau
    dev = float(jnp.linalg.norm(out["w"]))
    assert dev < 1.0 + 0.6, dev
    # and without clipping the attacker owns the mean
    naive = float(jnp.linalg.norm(jnp.mean(stacked["w"], axis=0)))
    assert naive > 1e5


def test_centered_clip_passes_honest_mean():
    """With all deviations under tau, one iteration IS the mean."""
    rng = np.random.default_rng(0)
    center = {"w": jnp.asarray(rng.normal(size=(6, 3)), jnp.float32)}
    stacked = {"w": center["w"][None] + jnp.asarray(rng.normal(size=(4, 6, 3)) * 0.01, jnp.float32)}
    out = centered_clip(stacked, center, tau=10.0, iters=1)
    np.testing.assert_allclose(
        np.asarray(out["w"]), np.asarray(jnp.mean(stacked["w"], axis=0)), atol=1e-5
    )


def test_centered_clip_experiment_reset():
    """ADVICE r2 (low): the clip center must not survive an experiment
    boundary — a second experiment would otherwise clip its round 0
    against the previous experiment's final model, pinning early progress
    to tau per round from a stale center."""
    agg = CenteredClip("test", tau=1.0)
    m = [ModelUpdate({"w": jnp.full((4,), v)}, [f"n{i}"], 1) for i, v in enumerate([1.0, 2.0])]
    agg.aggregate(m)
    assert agg._center is not None
    agg.clear()  # per-round clear keeps the center (history-aware by design)
    assert agg._center is not None
    agg.reset_experiment()  # experiment boundary drops it
    assert agg._center is None


class _ByzantineLearner(JaxLearner):
    """fit() discards the real update and emits huge Gaussian noise."""

    def fit(self):
        super().fit()
        key = jax.random.PRNGKey(666)
        self.params = jax.tree.map(
            lambda x: jax.random.normal(key, x.shape, x.dtype) * 100.0, self.params
        )


@pytest.mark.slow
def test_host_centered_clip_resists_byzantine_gossip():
    """3-node gossip federation, one ACTIVELY malicious node emitting
    100-sigma noise every round: CenteredClip keeps the federation training
    (individual-model shipping path, SUPPORTS_PARTIALS=False)."""
    full = FederatedDataset.synthetic_mnist(n_train=768, n_test=128)
    nodes = []
    for i in range(3):
        cls = _ByzantineLearner if i == 2 else JaxLearner
        learner = cls(mlp(seed=i), full.partition(i, 3), batch_size=64)
        nodes.append(Node(learner=learner, aggregator=CenteredClip(tau=5.0)))
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes)
    acc = nodes[0].learner.evaluate()["test_acc"]
    assert acc > 0.7, acc
    for n in nodes:
        n.stop()


@pytest.mark.slow
def test_spmd_centered_clip_resists_byzantine():
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    fed = SpmdFederation.from_dataset(
        mlp(), full, n_nodes=4, batch_size=64, vote=False,
        aggregator="clip", clip_tau=5.0,
    )
    poisoned = jax.tree.map(
        lambda x: x.at[0].set(jax.random.normal(jax.random.PRNGKey(0), x.shape[1:]) * 100.0),
        fed.params,
    )
    fed.params = poisoned
    fed.run(rounds=3)
    acc = fed.evaluate()["test_acc"]
    assert acc > 0.5, acc  # fedavg collapses to ~0.1 under this attack
