"""Megafleet: heap-vs-vectorized parity, bit-exact replay, fleet knobs.

The contract under test (ISSUE 15 acceptance): at 1k nodes on the
consensus task the vectorized engine reproduces the heap driver's merge
count and monotone version sequence EXACTLY, with the loss trajectory
inside a documented tolerance (flat: float-reassociation level — the
heap weights in Python f64 where the scan weights in f32; hierarchical:
the aggregate-interleaving tolerance, a few percent mid-waterfall,
<1e-2 relative at the tail); a run replays bit-exact from
``(seed, plan)``; a different seed diverges; and the Bonawitz knobs
(pace steering, selection, per-tier rate limits) have measurable,
deterministic effects.
"""

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    ByzantineSpec,
    CrashSpec,
    EdgeFault,
    FaultPlan,
    JoinSpec,
    LeaveSpec,
)
from p2pfl_tpu.federation.megafleet import FleetSpec, GradTask, MegaFleet
from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet

SEED = 1905


def _curves(res):
    t = np.asarray([x[0] for x in res.loss_curve])
    v = [x[1] for x in res.loss_curve]
    l = np.asarray([x[2] for x in res.loss_curve])
    return t, v, l


def _pair(n, cluster_size, **kw):
    """The same fleet through both drivers (export_spec parity hook)."""
    fleet = SimulatedAsyncFleet(
        n, seed=SEED, cluster_size=cluster_size, updates_per_node=4,
        slow_frac=0.1, local_lr=0.7, **kw,
    )
    spec = FleetSpec.from_sim(fleet)
    assert spec.link_delay == fleet.link_delay  # from_sim carries the clock
    mega = MegaFleet(
        spec, cluster_size=cluster_size, updates_per_node=4, local_lr=0.7, **kw,
    )
    assert mega.link_delay == fleet.link_delay
    return fleet.run(), mega.run()


# ---- kernel parity with the live buffer math ----


def test_staleness_weight_arr_matches_scalar():
    from p2pfl_tpu.federation.staleness import staleness_weight
    from p2pfl_tpu.ops.fleet_kernels import staleness_weight_arr

    taus = np.asarray([-3, 0, 1, 2, 7, 16, 100], np.int32)
    for alpha in (0.0, 0.5, 1.0, 2.0):
        arr = np.asarray(staleness_weight_arr(np.asarray(taus), alpha))
        ref = np.asarray(
            [staleness_weight(t, alpha) for t in taus], np.float32
        )
        np.testing.assert_allclose(arr, ref, rtol=1e-6)


def test_fold_window_matches_buffered_aggregator():
    """fold_window IS the live flush: same (origin,seq) sort, same
    fedavg/server_merge kernels — bit-identical on a real buffer, pad
    slots (weight 0, key PAD) folding as exact no-ops."""
    import jax.numpy as jnp

    from p2pfl_tpu.federation.buffer import BufferedAggregator
    from p2pfl_tpu.learning.weights import ModelUpdate
    from p2pfl_tpu.ops.fleet_kernels import PAD_KEY, fold_window

    dim, k = 8, 3
    rng = np.random.default_rng(7)
    init = rng.normal(size=dim).astype(np.float32)
    buf = BufferedAggregator(
        "t", {"p": init.copy()}, k=k, alpha=0.5, server_lr=0.7,
    )
    rows, weights, keys = [], [], []
    res = None
    # deliberately unsorted origins: the flush must sort, and so must we
    for j, (origin, samples) in enumerate([("b", 2), ("a", 5), ("c", 1)]):
        params = {"p": rng.normal(size=dim).astype(np.float32)}
        upd = ModelUpdate(params, [origin], samples)
        upd.version = (origin, 1, 0)  # τ = 0 everywhere: weight = samples
        rows.append(params["p"])
        weights.append(float(samples))
        keys.append(ord(origin))
        res = buf.offer(upd)
    assert res is not None and res.version == 1
    # pad to a wider window: zero weight + PAD_KEY must change nothing
    pad = 2
    rows = np.stack(rows + [np.zeros(dim, np.float32)] * pad)
    weights = np.asarray(weights + [0.0] * pad, np.float32)
    keys = np.asarray(keys + [int(PAD_KEY)] * pad, np.int32)
    out = np.asarray(
        fold_window(
            jnp.asarray(rows), jnp.asarray(weights), jnp.asarray(keys),
            jnp.asarray(init), 0.7,
        )
    )
    np.testing.assert_array_equal(out, np.asarray(res.params["p"]))


# ---- the 1k heap-parity anchor ----


def test_flat_parity_1k():
    heap, mega = _pair(1000, 0)
    assert mega.merges == heap.merges
    ht, hv, hl = _curves(heap)
    mt, mv, ml = _curves(mega)
    assert mv == hv  # monotone version sequence, exactly the heap's
    assert mv == sorted(mv) and len(set(mv)) == len(mv)
    # mint times agree to f32 time resolution; losses to reassociation
    # tolerance (measured 2e-7 relative — pinned with margin)
    np.testing.assert_allclose(mt, ht, atol=1e-4)
    np.testing.assert_allclose(ml, hl, rtol=0, atol=float(hl.max()) * 1e-5)
    assert abs(mega.final_loss() - heap.final_loss()) <= 1e-5 * heap.final_loss()
    np.testing.assert_allclose(
        np.asarray(mega.params["w"]), np.asarray(heap.params["w"]),
        rtol=0, atol=1e-5,
    )


def test_hier_parity_1k():
    heap, mega = _pair(1000, 32)
    assert mega.merges == heap.merges
    ht, hv, hl = _curves(heap)
    mt, mv, ml = _curves(mega)
    assert mv == hv
    assert mv == sorted(mv) and len(set(mv)) == len(mv)
    # the documented hierarchical tolerance: aggregate arrivals may
    # interleave differently within one link_delay in-flight window, so
    # mid-waterfall losses differ at the few-percent level while the
    # tail converges (measured: maxrel 0.09 mid-curve, 3.5e-4 final)
    np.testing.assert_allclose(ml, hl, rtol=0, atol=float(hl.max()) * 0.15)
    assert (
        abs(mega.final_loss() - heap.final_loss())
        <= 1e-2 * max(heap.final_loss(), 1e-9)
    )


def test_hier_parity_is_exact_under_wide_staleness_bound():
    """With the staleness bound too wide for boundary reorderings to
    flip an admission, hier merge counts stay exact at default settings
    too — this pins that the counts do not depend on the bound."""
    heap, mega = _pair(300, 16, max_staleness=10**6)
    assert mega.merges == heap.merges
    assert [x[1] for x in mega.loss_curve] == [x[1] for x in heap.loss_curve]


# ---- replay determinism ----


def test_replay_bit_exact_and_seed_divergence():
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.05, jitter=0.002))
    spec = FleetSpec.synth(2000, seed=SEED, slow_frac=0.1)

    def drive(s):
        return MegaFleet(
            s, cluster_size=64, k=8, updates_per_node=4, local_lr=0.7,
            plan=plan,
        ).run()

    a, b = drive(spec), drive(spec)
    assert a.merges == b.merges
    assert a.loss_curve == b.loss_curve  # float-equal: bit-exact replay
    assert a.updates_dropped_wire == b.updates_dropped_wire > 0
    assert a.staleness_hist_edge == b.staleness_hist_edge
    np.testing.assert_array_equal(a.params["w"], b.params["w"])

    c = drive(FleetSpec.synth(2000, seed=SEED + 1, slow_frac=0.1))
    assert c.loss_curve != a.loss_curve  # a different seed must diverge


def test_fault_plan_mapping():
    spec = FleetSpec.synth(400, seed=SEED)
    crash = {
        "sim-0007": CrashSpec(stage="AsyncTrainStage", round_no=2),
        "sim-0011": CrashSpec(stage="TrainStage", round_no=1),  # sync: inert
        # past the schedule: never enters AsyncTrainStage, never fires
        "sim-0013": CrashSpec(stage="AsyncTrainStage", round_no=9),
    }
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.1), crashes=crash)
    res = MegaFleet(
        spec, cluster_size=0, k=8, updates_per_node=4, plan=plan
    ).run()
    # the async-stage victim stops after 2 of 4 updates; the sync-stage
    # spec never fires (heap semantics); drops hit the counter
    assert res.n_events == 400 * 4 - 2
    assert res.updates_dropped_wire > 0
    assert res.crashed == ["sim-0007"]

    # the full vectorized fault algebra CONSTRUCTS (byzantine payload
    # kinds, duplicates, churn — each runs through counter grids now)...
    n = spec.n
    for good in (
        FaultPlan(seed=SEED, default=EdgeFault(duplicate=0.5)),
        FaultPlan(
            seed=SEED, byzantine={"sim-0002": ByzantineSpec(kind="sign_flip")}
        ),
        FaultPlan(seed=SEED, joins={f"sim-{n - 1:04d}": JoinSpec(at_s=3.0)}),
        FaultPlan(seed=SEED, leaves={"sim-0005": LeaveSpec(at_s=2.0)}),
    ):
        MegaFleet(spec, plan=good)

    # ...while per-edge overrides, pairwise cuts, stateful attacker
    # kinds and the stateful churn combinations still route to the heap
    with pytest.raises(ValueError, match="per-edge"):
        MegaFleet(spec, plan=FaultPlan(seed=SEED, edges={("a", "b"): EdgeFault(drop=1.0)}))
    with pytest.raises(ValueError, match="heap driver"):
        MegaFleet(
            spec, plan=FaultPlan(seed=SEED, partitions=[("sim-0001", "sim-0002")])
        )
    with pytest.raises(ValueError, match="heap driver"):
        MegaFleet(
            spec,
            plan=FaultPlan(
                seed=SEED, byzantine={"sim-0002": ByzantineSpec(kind="equivocate")}
            ),
        )
    churny = dict(joins={f"sim-{n - 1:04d}": JoinSpec(at_s=3.0)})
    with pytest.raises(ValueError, match="heap driver"):
        MegaFleet(
            spec,
            plan=FaultPlan(
                seed=SEED,
                byzantine={"sim-0002": ByzantineSpec(kind="sign_flip")},
                **churny,
            ),
        )
    with pytest.raises(ValueError, match="heap driver"):
        MegaFleet(spec, plan=FaultPlan(seed=SEED, **churny), fold="median")
    with pytest.raises(ValueError, match="heap driver"):
        MegaFleet(
            spec,
            plan=FaultPlan(seed=SEED, slow_nodes={"sim-0003": 5.0}, **churny),
        )
    with pytest.raises(ValueError, match="heap driver"):
        MegaFleet(spec, fold="krum-screen")


def test_slow_nodes_apply_on_synth_specs():
    """plan.slow_nodes must reach the vectorized engine even when the
    spec doesn't carry them (synth exports zeros) — and fold
    idempotently (by max) when it does (export_spec already folded the
    same plan)."""
    spec = FleetSpec.synth(200, seed=SEED)
    plan = FaultPlan(seed=SEED, slow_nodes={"sim-0001": 5.0, "sim-0003": 2.0})
    base = MegaFleet(spec, cluster_size=16, k=4, local_lr=0.7).run()
    slowed = MegaFleet(spec, cluster_size=16, k=4, local_lr=0.7, plan=plan).run()
    assert slowed.loss_curve != base.loss_curve
    again = MegaFleet(spec, cluster_size=16, k=4, local_lr=0.7, plan=plan).run()
    assert again.loss_curve == slowed.loss_curve


def test_aggregate_sends_see_the_fault_plan():
    """With every client its own regional (cluster_size=1), client
    self-offers bypass the wire and ALL traffic is regional→root
    aggregate sends — the heap routes that hop through _edge_verdict,
    so the scan's drop verdicts must reach it too."""
    spec = FleetSpec.synth(64, seed=SEED)
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.5))
    base = MegaFleet(spec, cluster_size=1, k=4, local_lr=0.7).run()
    res = MegaFleet(spec, cluster_size=1, k=4, local_lr=0.7, plan=plan).run()
    assert res.updates_dropped_wire > 0  # aggregate drops, not client ones
    assert res.merges < base.merges
    again = MegaFleet(spec, cluster_size=1, k=4, local_lr=0.7, plan=plan).run()
    assert again.loss_curve == res.loss_curve  # still replay-exact


def test_fault_verdicts_survive_zero_link_delay():
    """The src==dst bypass keys on the regional mask, not on a delay
    value — at link_delay=0 every hop collapses to 0 but edge sends must
    still see the plan's drop verdicts."""
    spec = FleetSpec.synth(300, seed=SEED)
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.5))
    res = MegaFleet(
        spec, cluster_size=16, k=4, local_lr=0.7, link_delay=0.0, plan=plan
    ).run()
    assert res.updates_dropped_wire > 0


# ---- the Bonawitz fleet knobs ----


def test_pace_steering_spreads_the_first_wave():
    spec = FleetSpec.synth(2000, seed=SEED)
    base = MegaFleet(spec, cluster_size=64, k=8, local_lr=0.7).run()
    paced = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7, pace_window=1.0
    ).run()
    # same work, staggered: the first mint lands later, the run is
    # deterministic, and the staleness profile shifts measurably
    assert paced.merges > 0
    assert paced.loss_curve[0][0] > base.loss_curve[0][0]
    assert paced.staleness_hist_edge != base.staleness_hist_edge
    again = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7, pace_window=1.0
    ).run()
    assert again.loss_curve == paced.loss_curve


def test_selection_over_provisioning_gate():
    spec = FleetSpec.synth(2000, seed=SEED)
    full = MegaFleet(spec, cluster_size=64, k=8, local_lr=0.7).run()
    half = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7, select_frac=0.5
    ).run()
    assert half.unselected > 0
    assert half.n_events < full.n_events
    assert half.merges < full.merges
    # unselected slots idle the device: nothing else may shift
    assert half.rate_limited == 0 and half.updates_dropped_wire == 0


def test_per_tier_rate_limit():
    spec = FleetSpec.synth(2000, seed=SEED)
    free = MegaFleet(spec, cluster_size=64, k=8, local_lr=0.7).run()
    limited = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7,
        rate_limit_regional=0.05, rate_limit_global=0.05,
    ).run()
    assert limited.rate_limited > 0
    assert limited.merges < free.merges
    assert limited.buffered < free.buffered


# ---- scale + structure smoke ----


def test_scale_smoke_20k():
    """A 20k-client hierarchical drive: structure invariants at a scale
    the heap cannot reach in test time (the 1M row lives in
    BENCH_ASYNC; this pins the same engine path at CI cost)."""
    spec = FleetSpec.synth(20_000, seed=SEED, slow_frac=0.1)
    res = MegaFleet(
        spec, cluster_size=512, k=32, updates_per_node=4, local_lr=0.7
    ).run()
    t, v, l = _curves(res)
    assert res.merges == res.version == v[-1]
    assert v == sorted(v) and len(set(v)) == len(v)
    assert np.all(np.diff(t) >= 0)  # mint times monotone
    assert l[-1] < l[0] * 0.05  # the fleet actually converges
    assert res.regional_merges > res.merges
    # every regional flush consumed exactly K=32 admitted contributions;
    # anything left over is an unflushed partial window per regional
    n_regionals = len(MegaFleet(spec, cluster_size=512, k=32).router.regionals)
    assert 32 * res.regional_merges <= res.buffered
    assert res.buffered < 32 * res.regional_merges + 32 * n_regionals
    assert res.clients_per_sec > 0


# ---- satellites: copy-on-write + the parity hook ----


def test_simfleet_copy_on_write_aliases_deliveries():
    """Pass-through sites alias: two edges that adopted the same global
    hold the SAME tree object (pre-CoW every delivery deep-copied), and
    the final result aliases the root buffer's params."""
    fleet = SimulatedAsyncFleet(
        8, seed=3, cluster_size=0, updates_per_node=3, local_lr=0.7
    )
    res = fleet.run()
    root = fleet.router.root
    edges = [
        a for a, n in fleet.nodes.items()
        if a != root and n.global_params is not None and n.known_version == res.version
    ]
    assert len(edges) >= 2
    first = fleet.nodes[edges[0]].global_params
    assert all(fleet.nodes[a].global_params is first for a in edges[1:])
    assert res.params is fleet._buffers[root]["global"].snapshot()[0]


def test_export_spec_matches_population():
    fleet = SimulatedAsyncFleet(
        32, seed=SEED, cluster_size=8, updates_per_node=2, slow_frac=0.25
    )
    spec = fleet.export_spec()
    addrs = sorted(fleet.nodes)
    assert spec["durations"].shape == (32,)
    for j, a in enumerate(addrs):
        assert spec["durations"][j] == fleet.nodes[a].duration
        assert spec["num_samples"][j] == fleet.nodes[a].num_samples
    np.testing.assert_array_equal(
        spec["targets"][5], fleet._target(fleet.nodes[addrs[5]].idx)
    )
    fleet._init = {"w": np.zeros(4, np.float32), "b": np.zeros(2, np.float32)}
    with pytest.raises(ValueError, match="consensus-task layout"):
        fleet.export_spec()

    custom = SimulatedAsyncFleet(
        8, seed=SEED, cluster_size=0, train_fn=lambda i, p, r: p
    )
    with pytest.raises(ValueError, match="no vectorized twin"):
        custom.export_spec()

    big = SimulatedAsyncFleet(10_001, seed=SEED, cluster_size=32)
    with pytest.raises(ValueError, match="4-digit address"):
        big.export_spec()


# ---- the chunked engine (ISSUE 16): bit-identity, fold keys, faults ----


def test_chunked_engine_bit_identical_to_per_event():
    """The chunked engine's batched gather → segment-fold → predicated
    scatter decomposition must change NOTHING: flat results are
    bit-identical to the per-event reference scan across chunk sizes
    that do and don't divide the event count (masked-tail rule), and the
    hierarchical engine matches bitwise too on this geometry."""
    spec = FleetSpec.synth(500, seed=SEED, dim=8)

    def run(chunk, cluster):
        return MegaFleet(
            spec, cluster_size=cluster, k=8, updates_per_node=4,
            local_lr=0.7, chunk=chunk,
        ).run()

    ref = run(1, 0)
    for chunk in (7, 48, 256):
        got = run(chunk, 0)
        assert got.merges == ref.merges and got.version == ref.version
        assert got.loss_curve == ref.loss_curve
        np.testing.assert_array_equal(got.params["w"], ref.params["w"])

    href = run(1, 32)
    hgot = run(48, 32)
    assert hgot.merges == href.merges
    assert hgot.regional_merges == href.regional_merges
    assert hgot.loss_curve == href.loss_curve
    np.testing.assert_array_equal(hgot.params["w"], href.params["w"])


def test_fold_key_two_word_order_at_int32_boundary():
    """Regression for the retired product fold key ``ii*(M+1)+mm+1``:
    past ``n·(M+1) > 2^31`` it overflowed int32 (the engine used to
    REFUSE such populations). The two-word ``(key_hi, key_lo)`` lexsort
    must reproduce the heap's (origin, seq) tuple order verbatim at
    indices where the product formula wraps negative."""
    import jax.numpy as jnp

    from p2pfl_tpu.ops.fleet_kernels import fold_window

    dim, M = 4, 4
    # client indices deep in the would-overflow regime: ii*(M+1)+mm+1
    # exceeds int32 for every row here
    his = np.asarray(
        [2**31 - 2, 2**30 + 5, 2**31 - 2, 2**30 + 5, 2**29], np.int64
    )
    los = np.asarray([3, 1, 1, 2, 4], np.int64)
    assert ((his * (M + 1) + los) > np.iinfo(np.int32).max).all()
    rng = np.random.default_rng(3)
    rows = rng.normal(size=(5, dim)).astype(np.float32)
    weights = rng.uniform(1, 2, size=5).astype(np.float32)
    prev = np.zeros(dim, np.float32)

    out = np.asarray(
        fold_window(
            jnp.asarray(rows), jnp.asarray(weights),
            jnp.asarray(los.astype(np.int32)), jnp.asarray(prev), 0.7,
            keys_hi=jnp.asarray((his - 2**31).astype(np.int32)),
        )
    )
    # reference: fold in the heap's tuple order via small rank-compressed
    # keys (tuple order is all the fold may depend on)
    order = sorted(range(5), key=lambda j: (his[j], los[j]))
    ranks = np.empty(5, np.int32)
    ranks[order] = np.arange(5, dtype=np.int32)
    ref = np.asarray(
        fold_window(
            jnp.asarray(rows), jnp.asarray(weights), jnp.asarray(ranks),
            jnp.asarray(prev), 0.7,
        )
    )
    np.testing.assert_array_equal(out, ref)


def test_megafleet_accepts_overflow_scale_key_space():
    """The engine itself must not refuse populations whose
    ``n × (updates+1)`` product passes int32 (the old raise at
    megafleet.py) — key words stay per-field int32 regardless of n."""
    spec = FleetSpec.synth(500, seed=SEED, dim=4)
    mf = MegaFleet(spec, cluster_size=0, k=8, updates_per_node=4)
    # simulated: the old product key for the LAST event of a 600M-client
    # fleet would overflow; the two-word key never multiplies
    n_huge, M = 600_000_000, 4
    assert n_huge * (M + 1) > np.iinfo(np.int32).max
    assert mf.run().version > 0  # and the real engine runs unchanged


def test_byzantine_parity_1k():
    """Deterministic corruption kinds (sign_flip / scale) at the edge
    seam: the vectorized payload transforms must reproduce the heap's
    byz_corrupt_update runs exactly — corruption counts and merge
    decisions EXACT, losses at reassociation tolerance."""
    byz = {
        "sim-0003": ByzantineSpec(kind="sign_flip"),
        "sim-0007": ByzantineSpec(kind="sign_flip"),
        "sim-0011": ByzantineSpec(kind="scale", lam=25.0),
    }
    heap, mega = _pair(1000, 0, plan=FaultPlan(seed=SEED, byzantine=byz))
    assert mega.byz_corrupted == heap.byz_corrupted > 0
    assert mega.merges == heap.merges
    _, hv, hl = _curves(heap)
    _, mv, ml = _curves(mega)
    assert mv == hv
    np.testing.assert_allclose(ml, hl, rtol=0, atol=float(hl.max()) * 1e-5)


def test_byzantine_noise_parity_1k():
    """The noise kind draws from driver-specific streams, so payloads
    differ — but admission never reads the payload: corruption counts
    and merge decisions stay EXACT, and both drivers land on the same
    fixed point (the zero-mean noise washes out of the tail)."""
    byz = {
        "sim-0003": ByzantineSpec(kind="noise", noise_std=5.0),
        "sim-0007": ByzantineSpec(kind="noise", noise_std=5.0),
    }
    heap, mega = _pair(1000, 0, plan=FaultPlan(seed=SEED, byzantine=byz))
    assert mega.byz_corrupted == heap.byz_corrupted > 0
    assert mega.merges == heap.merges
    assert [x[1] for x in mega.loss_curve] == [x[1] for x in heap.loss_curve]
    assert (
        abs(mega.final_loss() - heap.final_loss())
        <= 5e-2 * max(heap.final_loss(), 1e-9)
    )


def test_byzantine_hier_aggregate_seam():
    """An ATTACKER ELECTED REGIONAL corrupts its regional→root aggregate
    sends (the heap routes those through the same byz_corrupt_update
    seam); honest self-offers stay honest. Counts exact, tail within the
    hier tolerance."""
    byz = {
        "sim-0000": ByzantineSpec(kind="sign_flip"),  # elected regional
        "sim-0030": ByzantineSpec(kind="sign_flip"),
        "sim-0055": ByzantineSpec(kind="sign_flip"),
    }
    heap, mega = _pair(200, 25, plan=FaultPlan(seed=SEED, byzantine=byz))
    assert mega.byz_corrupted == heap.byz_corrupted > 0
    assert mega.merges == heap.merges
    assert (
        abs(mega.final_loss() - heap.final_loss())
        <= 1e-2 * max(heap.final_loss(), 1e-9)
    )


def test_robust_folds_parity_and_defense_1k():
    """The window fold swapped to buffered_robust_merge's trimmed-mean /
    median under a 10% scale-attacker population: parity with the heap
    (which flushes through Settings.ASYNC_ROBUST_AGG) stays at
    reassociation tolerance, and median actually DEFENDS — its final
    loss beats fedavg's under the same attack."""
    from p2pfl_tpu.settings import Settings

    byz = {
        f"sim-{i:04d}": ByzantineSpec(kind="scale", lam=50.0)
        for i in range(0, 1000, 10)
    }
    plan = FaultPlan(seed=SEED, byzantine=byz)
    finals = {}
    try:
        for fold in ("fedavg", "trimmed-mean", "median"):
            Settings.ASYNC_ROBUST_AGG = fold
            heap, mega = _pair(1000, 0, plan=plan)
            assert mega.merges == heap.merges
            _, hv, hl = _curves(heap)
            _, mv, ml = _curves(mega)
            assert mv == hv
            np.testing.assert_allclose(
                ml, hl, rtol=0, atol=float(hl.max()) * 1e-5
            )
            finals[fold] = mega.final_loss()
    finally:
        Settings.ASYNC_ROBUST_AGG = "fedavg"
    assert finals["median"] < finals["fedavg"]
    assert finals["trimmed-mean"] < finals["fedavg"]


def test_duplicates_are_counted_noops_1k():
    """default.duplicate injects replayed (origin, seq) triples; the
    version vector dedups every one, so a duplicate plan must be
    RESULT-INVARIANT in both drivers while the injection counters
    record the chaos actually exercised."""
    plan = FaultPlan(seed=SEED, default=EdgeFault(duplicate=0.3))
    h0, m0 = _pair(1000, 0)
    h1, m1 = _pair(1000, 0, plan=plan)
    assert h1.duplicates_injected > 0 and m1.duplicates_injected > 0
    assert h1.merges == h0.merges and m1.merges == m0.merges
    assert h1.loss_curve == h0.loss_curve
    assert m1.loss_curve == m0.loss_curve
    np.testing.assert_array_equal(m1.params["w"], m0.params["w"])


def test_duplicates_hit_the_aggregate_seam():
    """Hierarchical: the regional→root hop runs the same duplicate
    verdicts (per-(regional, up_seq) grid) — counted, still no-ops."""
    plan = FaultPlan(seed=SEED, default=EdgeFault(duplicate=0.5))
    h0, m0 = _pair(300, 16)
    h1, m1 = _pair(300, 16, plan=plan)
    assert h1.duplicates_injected > 0 and m1.duplicates_injected > 0
    assert m1.merges == m0.merges and m1.loss_curve == m0.loss_curve
    assert h1.merges == h0.merges and h1.loss_curve == h0.loss_curve


def _churn_pair(n, cluster, plan, extra, dim=16, **kw):
    fleet = SimulatedAsyncFleet(
        n, seed=SEED, cluster_size=cluster, updates_per_node=4,
        local_lr=0.7, plan=plan, dim=dim, **kw,
    )
    spec = FleetSpec.from_sim(fleet, extra=extra)  # BEFORE run: joiners pend
    return fleet.run(), MegaFleet(
        spec, cluster_size=cluster, updates_per_node=4, local_lr=0.7,
        plan=plan, **kw,
    ).run()


def test_churn_parity_1k():
    """joins/leaves as time-indexed liveness with TierRouter re-derived
    at every membership boundary: joined/left rosters EXACT, failovers
    EXACT, merge count and version sequence EXACT on this geometry
    (non-regional leavers), loss tail inside the churn tolerance
    (documented divergences: joiner bootstrap adoption, in-flight loss
    at a leaver)."""
    n = 1000
    joins = {
        f"sim-{i:04d}": JoinSpec(at_s=2.0 + 0.1 * (i - n))
        for i in range(n, n + 8)
    }
    leaves = {
        "sim-0005": LeaveSpec(at_s=2.5, graceful=True),
        "sim-0033": LeaveSpec(at_s=3.0, graceful=False),
    }
    plan = FaultPlan(seed=SEED, joins=joins, leaves=leaves)
    heap, mega = _churn_pair(n, 32, plan, extra=8)
    assert mega.joined == heap.joined
    assert mega.left == heap.left
    assert mega.failovers == heap.failovers
    assert mega.merges == heap.merges
    assert [x[1] for x in mega.loss_curve] == [x[1] for x in heap.loss_curve]
    assert (
        abs(mega.final_loss() - heap.final_loss())
        <= 5e-2 * max(heap.final_loss(), 1e-9)
    )


def test_churn_root_failover_parity():
    """The global root leaving gracefully: both drivers re-elect (ONE
    failover) and mint the same number of globals. The heap additionally
    hands the in-flight global buffer to the successor — a documented
    divergence in the merge COUNTER, not the version sequence."""
    plan = FaultPlan(
        seed=SEED, leaves={"sim-0000": LeaveSpec(at_s=2.2, graceful=True)}
    )
    heap, mega = _churn_pair(200, 25, plan, extra=0, k=4, dim=8)
    assert mega.failovers == heap.failovers == 1
    assert mega.left == heap.left == ["sim-0000"]
    assert mega.version == heap.version
    assert (
        abs(mega.final_loss() - heap.final_loss())
        <= 0.2 * max(heap.final_loss(), 1e-9)
    )


def test_churn_flat_parity():
    """Flat topology churn (joiners stream into the single buffer):
    merges and version sequence EXACT."""
    n = 300
    joins = {
        f"sim-{i:04d}": JoinSpec(at_s=1.5 + 0.2 * (i - n))
        for i in range(n, n + 5)
    }
    plan = FaultPlan(seed=SEED, joins=joins)
    heap, mega = _churn_pair(n, 0, plan, extra=5)
    assert mega.joined == heap.joined
    assert mega.merges == heap.merges
    assert [x[1] for x in mega.loss_curve] == [x[1] for x in heap.loss_curve]


# ---- the vmapped real-gradient learner (GradTask) ----


def test_grad_train_one_matches_jax_learner_epoch():
    """fk.make_grad_fns' train_one IS JaxLearner's epoch math: the same
    scan of SGD steps train_epoch compiles (optax.sgd + apply_updates on
    a Dense stack), here on the flat parameter layout. Bit-close on the
    same seeded batches."""
    import flax.linen as nn
    import jax.numpy as jnp

    from p2pfl_tpu.learning.learner import sgd, train_epoch
    from p2pfl_tpu.ops import fleet_kernels as fk

    din, nout, bs, steps, lr = 6, 3, 4, 3, 0.5

    class _Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(nout)(x)

    gen_batch, train_one, _ = fk.make_grad_fns(
        "linear", din, nout, 0, bs, steps, lr, data_seed=5
    )
    task = GradTask(kind="linear", d_in=din, n_out=nout, batch=bs,
                    steps=steps, data_seed=5)
    mu, tw, tb, _, _ = task.arrays(1)
    xs, ys = gen_batch(0, 1, jnp.asarray(mu[0]), jnp.asarray(tw), jnp.asarray(tb))

    rng = np.random.default_rng(11)
    w0 = rng.normal(size=(din, nout)).astype(np.float32)
    b0 = rng.normal(size=nout).astype(np.float32)
    flat0 = jnp.asarray(np.concatenate([w0.ravel(), b0]))
    out_flat = np.asarray(train_one(flat0, xs, ys))

    module = _Lin()
    params = {"Dense_0": {"kernel": jnp.asarray(w0), "bias": jnp.asarray(b0)}}
    tx = sgd(lr)
    params, _, _ = train_epoch(params, tx.init(params), xs, ys, module, tx)
    ref = np.concatenate([
        np.asarray(params["Dense_0"]["kernel"]).ravel(),
        np.asarray(params["Dense_0"]["bias"]),
    ])
    np.testing.assert_allclose(out_flat, ref, atol=1e-6)


def test_grad_task_single_client_chunked_trajectory():
    """One client, K=1, server_lr=1, α=0: every mint IS the client's
    next local round, so the chunked engine's G trajectory must follow
    the sequential train_one chain on the same counter-keyed batches
    (1-based round == the fold key's key_lo)."""
    import jax.numpy as jnp

    from p2pfl_tpu.ops import fleet_kernels as fk

    task = GradTask(kind="linear", d_in=6, n_out=3, batch=4, steps=3,
                    data_seed=5)
    spec = FleetSpec.synth(1, seed=3, dim=task.param_dim())
    res = MegaFleet(
        spec, cluster_size=0, k=1, updates_per_node=4, alpha=0.0,
        server_lr=1.0, task=task, link_delay=0.0, chunk=48,
    ).run()
    assert res.version == 4

    gen_batch, train_one, _ = fk.make_grad_fns(
        "linear", 6, 3, 0, 4, 3, 0.5, data_seed=5
    )
    mu, tw, tb, _, _ = task.arrays(1)
    p = jnp.zeros(task.param_dim(), jnp.float32)
    for m in range(1, 5):
        xs, ys = gen_batch(0, m, jnp.asarray(mu[0]), jnp.asarray(tw), jnp.asarray(tb))
        p = train_one(p, xs, ys)
    np.testing.assert_allclose(res.params["w"], np.asarray(p), atol=1e-6)


def test_grad_task_mlp_runs_and_learns():
    """The mlp task kind wires through the same engine: eval-set CE
    falls from init on a small fleet."""
    task = GradTask(kind="mlp", d_in=6, n_out=3, hidden=5, batch=4,
                    steps=2, data_seed=9)
    spec = FleetSpec.synth(40, seed=3, dim=task.param_dim())
    res = MegaFleet(
        spec, cluster_size=0, k=4, updates_per_node=4, task=task,
        local_lr=0.7,
    ).run()
    losses = [x[2] for x in res.loss_curve]
    assert len(losses) == res.version
    assert losses[-1] < losses[0]


def test_grad_task_heap_parity_1k():
    """The 1k heap-parity pin for the gradient grid: the heap driver
    runs a vectorized-twin train_fn (same make_grad_fns kernels, 1-based
    per-node round counters matching key_lo) and the chunked engine must
    reproduce its merge decisions exactly with params at float
    tolerance."""
    from collections import defaultdict

    import jax
    import jax.numpy as jnp
    import optax

    from p2pfl_tpu.ops import fleet_kernels as fk

    task = GradTask(kind="linear", d_in=6, n_out=3, batch=4, steps=2,
                    data_seed=5)
    pd = task.param_dim()
    gen_batch, train_one, _ = fk.make_grad_fns(
        "linear", 6, 3, 0, 4, 2, 0.7, data_seed=5
    )
    t1j = jax.jit(train_one)
    mu, tw, tb, xe, ye = task.arrays(1000)
    muj, twj, tbj = jnp.asarray(mu), jnp.asarray(tw), jnp.asarray(tb)
    counters: dict = defaultdict(int)

    def train_fn(idx, params, rng):
        counters[idx] += 1
        xs, ys = gen_batch(idx, counters[idx], muj[idx], twj, tbj)
        return {"w": np.asarray(t1j(jnp.asarray(params["w"]), xs, ys))}

    def loss_fn(params):
        lg = fk.grad_logits("linear", 6, 3, 0, jnp.asarray(params["w"]),
                            jnp.asarray(xe))
        return float(
            optax.softmax_cross_entropy_with_integer_labels(
                lg, jnp.asarray(ye)
            ).mean()
        )

    fleet = SimulatedAsyncFleet(
        1000, seed=SEED, cluster_size=0, updates_per_node=4, k=8,
        local_lr=0.7, dim=pd, train_fn=train_fn, loss_fn=loss_fn,
        init_params={"w": np.zeros(pd, np.float32)},
    )
    spec = FleetSpec.from_sim(fleet, allow_custom=True)
    heap = fleet.run()
    mega = MegaFleet(
        spec, cluster_size=0, k=8, updates_per_node=4, local_lr=0.7,
        task=task,
    ).run()
    assert mega.merges == heap.merges
    _, hv, hl = _curves(heap)
    _, mv, ml = _curves(mega)
    assert mv == hv
    np.testing.assert_allclose(ml, hl, rtol=0, atol=float(max(hl.max(), 1e-9)) * 1e-4)
    np.testing.assert_allclose(
        np.asarray(mega.params["w"]), np.asarray(heap.params["w"]), atol=1e-5
    )


def test_grad_task_dim_mismatch_raises():
    task = GradTask(kind="linear", d_in=6, n_out=3)
    spec = FleetSpec.synth(10, seed=3, dim=4)
    with pytest.raises(ValueError, match="param"):
        MegaFleet(spec, task=task)
