"""Megafleet: heap-vs-vectorized parity, bit-exact replay, fleet knobs.

The contract under test (ISSUE 15 acceptance): at 1k nodes on the
consensus task the vectorized engine reproduces the heap driver's merge
count and monotone version sequence EXACTLY, with the loss trajectory
inside a documented tolerance (flat: float-reassociation level — the
heap weights in Python f64 where the scan weights in f32; hierarchical:
the aggregate-interleaving tolerance, a few percent mid-waterfall,
<1e-2 relative at the tail); a run replays bit-exact from
``(seed, plan)``; a different seed diverges; and the Bonawitz knobs
(pace steering, selection, per-tier rate limits) have measurable,
deterministic effects.
"""

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import CrashSpec, EdgeFault, FaultPlan
from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet
from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet

SEED = 1905


def _curves(res):
    t = np.asarray([x[0] for x in res.loss_curve])
    v = [x[1] for x in res.loss_curve]
    l = np.asarray([x[2] for x in res.loss_curve])
    return t, v, l


def _pair(n, cluster_size, **kw):
    """The same fleet through both drivers (export_spec parity hook)."""
    fleet = SimulatedAsyncFleet(
        n, seed=SEED, cluster_size=cluster_size, updates_per_node=4,
        slow_frac=0.1, local_lr=0.7, **kw,
    )
    spec = FleetSpec.from_sim(fleet)
    assert spec.link_delay == fleet.link_delay  # from_sim carries the clock
    mega = MegaFleet(
        spec, cluster_size=cluster_size, updates_per_node=4, local_lr=0.7, **kw,
    )
    assert mega.link_delay == fleet.link_delay
    return fleet.run(), mega.run()


# ---- kernel parity with the live buffer math ----


def test_staleness_weight_arr_matches_scalar():
    from p2pfl_tpu.federation.staleness import staleness_weight
    from p2pfl_tpu.ops.fleet_kernels import staleness_weight_arr

    taus = np.asarray([-3, 0, 1, 2, 7, 16, 100], np.int32)
    for alpha in (0.0, 0.5, 1.0, 2.0):
        arr = np.asarray(staleness_weight_arr(np.asarray(taus), alpha))
        ref = np.asarray(
            [staleness_weight(t, alpha) for t in taus], np.float32
        )
        np.testing.assert_allclose(arr, ref, rtol=1e-6)


def test_fold_window_matches_buffered_aggregator():
    """fold_window IS the live flush: same (origin,seq) sort, same
    fedavg/server_merge kernels — bit-identical on a real buffer, pad
    slots (weight 0, key PAD) folding as exact no-ops."""
    import jax.numpy as jnp

    from p2pfl_tpu.federation.buffer import BufferedAggregator
    from p2pfl_tpu.learning.weights import ModelUpdate
    from p2pfl_tpu.ops.fleet_kernels import PAD_KEY, fold_window

    dim, k = 8, 3
    rng = np.random.default_rng(7)
    init = rng.normal(size=dim).astype(np.float32)
    buf = BufferedAggregator(
        "t", {"p": init.copy()}, k=k, alpha=0.5, server_lr=0.7,
    )
    rows, weights, keys = [], [], []
    res = None
    # deliberately unsorted origins: the flush must sort, and so must we
    for j, (origin, samples) in enumerate([("b", 2), ("a", 5), ("c", 1)]):
        params = {"p": rng.normal(size=dim).astype(np.float32)}
        upd = ModelUpdate(params, [origin], samples)
        upd.version = (origin, 1, 0)  # τ = 0 everywhere: weight = samples
        rows.append(params["p"])
        weights.append(float(samples))
        keys.append(ord(origin))
        res = buf.offer(upd)
    assert res is not None and res.version == 1
    # pad to a wider window: zero weight + PAD_KEY must change nothing
    pad = 2
    rows = np.stack(rows + [np.zeros(dim, np.float32)] * pad)
    weights = np.asarray(weights + [0.0] * pad, np.float32)
    keys = np.asarray(keys + [int(PAD_KEY)] * pad, np.int32)
    out = np.asarray(
        fold_window(
            jnp.asarray(rows), jnp.asarray(weights), jnp.asarray(keys),
            jnp.asarray(init), 0.7,
        )
    )
    np.testing.assert_array_equal(out, np.asarray(res.params["p"]))


# ---- the 1k heap-parity anchor ----


def test_flat_parity_1k():
    heap, mega = _pair(1000, 0)
    assert mega.merges == heap.merges
    ht, hv, hl = _curves(heap)
    mt, mv, ml = _curves(mega)
    assert mv == hv  # monotone version sequence, exactly the heap's
    assert mv == sorted(mv) and len(set(mv)) == len(mv)
    # mint times agree to f32 time resolution; losses to reassociation
    # tolerance (measured 2e-7 relative — pinned with margin)
    np.testing.assert_allclose(mt, ht, atol=1e-4)
    np.testing.assert_allclose(ml, hl, rtol=0, atol=float(hl.max()) * 1e-5)
    assert abs(mega.final_loss() - heap.final_loss()) <= 1e-5 * heap.final_loss()
    np.testing.assert_allclose(
        np.asarray(mega.params["w"]), np.asarray(heap.params["w"]),
        rtol=0, atol=1e-5,
    )


def test_hier_parity_1k():
    heap, mega = _pair(1000, 32)
    assert mega.merges == heap.merges
    ht, hv, hl = _curves(heap)
    mt, mv, ml = _curves(mega)
    assert mv == hv
    assert mv == sorted(mv) and len(set(mv)) == len(mv)
    # the documented hierarchical tolerance: aggregate arrivals may
    # interleave differently within one link_delay in-flight window, so
    # mid-waterfall losses differ at the few-percent level while the
    # tail converges (measured: maxrel 0.09 mid-curve, 3.5e-4 final)
    np.testing.assert_allclose(ml, hl, rtol=0, atol=float(hl.max()) * 0.15)
    assert (
        abs(mega.final_loss() - heap.final_loss())
        <= 1e-2 * max(heap.final_loss(), 1e-9)
    )


def test_hier_parity_is_exact_under_wide_staleness_bound():
    """With the staleness bound too wide for boundary reorderings to
    flip an admission, hier merge counts stay exact at default settings
    too — this pins that the counts do not depend on the bound."""
    heap, mega = _pair(300, 16, max_staleness=10**6)
    assert mega.merges == heap.merges
    assert [x[1] for x in mega.loss_curve] == [x[1] for x in heap.loss_curve]


# ---- replay determinism ----


def test_replay_bit_exact_and_seed_divergence():
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.05, jitter=0.002))
    spec = FleetSpec.synth(2000, seed=SEED, slow_frac=0.1)

    def drive(s):
        return MegaFleet(
            s, cluster_size=64, k=8, updates_per_node=4, local_lr=0.7,
            plan=plan,
        ).run()

    a, b = drive(spec), drive(spec)
    assert a.merges == b.merges
    assert a.loss_curve == b.loss_curve  # float-equal: bit-exact replay
    assert a.updates_dropped_wire == b.updates_dropped_wire > 0
    assert a.staleness_hist_edge == b.staleness_hist_edge
    np.testing.assert_array_equal(a.params["w"], b.params["w"])

    c = drive(FleetSpec.synth(2000, seed=SEED + 1, slow_frac=0.1))
    assert c.loss_curve != a.loss_curve  # a different seed must diverge


def test_fault_plan_mapping():
    spec = FleetSpec.synth(400, seed=SEED)
    crash = {
        "sim-0007": CrashSpec(stage="AsyncTrainStage", round_no=2),
        "sim-0011": CrashSpec(stage="TrainStage", round_no=1),  # sync: inert
        # past the schedule: never enters AsyncTrainStage, never fires
        "sim-0013": CrashSpec(stage="AsyncTrainStage", round_no=9),
    }
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.1), crashes=crash)
    res = MegaFleet(
        spec, cluster_size=0, k=8, updates_per_node=4, plan=plan
    ).run()
    # the async-stage victim stops after 2 of 4 updates; the sync-stage
    # spec never fires (heap semantics); drops hit the counter
    assert res.n_events == 400 * 4 - 2
    assert res.updates_dropped_wire > 0
    assert res.crashed == ["sim-0007"]

    for bad in (
        FaultPlan(seed=SEED, partitions=[("sim-0001", "sim-0002")]),
        FaultPlan(seed=SEED, edges={("a", "b"): EdgeFault(drop=1.0)}),
        FaultPlan(seed=SEED, default=EdgeFault(duplicate=0.5)),
    ):
        with pytest.raises(ValueError, match="heap driver"):
            MegaFleet(spec, plan=bad)


def test_slow_nodes_apply_on_synth_specs():
    """plan.slow_nodes must reach the vectorized engine even when the
    spec doesn't carry them (synth exports zeros) — and fold
    idempotently (by max) when it does (export_spec already folded the
    same plan)."""
    spec = FleetSpec.synth(200, seed=SEED)
    plan = FaultPlan(seed=SEED, slow_nodes={"sim-0001": 5.0, "sim-0003": 2.0})
    base = MegaFleet(spec, cluster_size=16, k=4, local_lr=0.7).run()
    slowed = MegaFleet(spec, cluster_size=16, k=4, local_lr=0.7, plan=plan).run()
    assert slowed.loss_curve != base.loss_curve
    again = MegaFleet(spec, cluster_size=16, k=4, local_lr=0.7, plan=plan).run()
    assert again.loss_curve == slowed.loss_curve


def test_aggregate_sends_see_the_fault_plan():
    """With every client its own regional (cluster_size=1), client
    self-offers bypass the wire and ALL traffic is regional→root
    aggregate sends — the heap routes that hop through _edge_verdict,
    so the scan's drop verdicts must reach it too."""
    spec = FleetSpec.synth(64, seed=SEED)
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.5))
    base = MegaFleet(spec, cluster_size=1, k=4, local_lr=0.7).run()
    res = MegaFleet(spec, cluster_size=1, k=4, local_lr=0.7, plan=plan).run()
    assert res.updates_dropped_wire > 0  # aggregate drops, not client ones
    assert res.merges < base.merges
    again = MegaFleet(spec, cluster_size=1, k=4, local_lr=0.7, plan=plan).run()
    assert again.loss_curve == res.loss_curve  # still replay-exact


def test_fault_verdicts_survive_zero_link_delay():
    """The src==dst bypass keys on the regional mask, not on a delay
    value — at link_delay=0 every hop collapses to 0 but edge sends must
    still see the plan's drop verdicts."""
    spec = FleetSpec.synth(300, seed=SEED)
    plan = FaultPlan(seed=SEED, default=EdgeFault(drop=0.5))
    res = MegaFleet(
        spec, cluster_size=16, k=4, local_lr=0.7, link_delay=0.0, plan=plan
    ).run()
    assert res.updates_dropped_wire > 0


# ---- the Bonawitz fleet knobs ----


def test_pace_steering_spreads_the_first_wave():
    spec = FleetSpec.synth(2000, seed=SEED)
    base = MegaFleet(spec, cluster_size=64, k=8, local_lr=0.7).run()
    paced = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7, pace_window=1.0
    ).run()
    # same work, staggered: the first mint lands later, the run is
    # deterministic, and the staleness profile shifts measurably
    assert paced.merges > 0
    assert paced.loss_curve[0][0] > base.loss_curve[0][0]
    assert paced.staleness_hist_edge != base.staleness_hist_edge
    again = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7, pace_window=1.0
    ).run()
    assert again.loss_curve == paced.loss_curve


def test_selection_over_provisioning_gate():
    spec = FleetSpec.synth(2000, seed=SEED)
    full = MegaFleet(spec, cluster_size=64, k=8, local_lr=0.7).run()
    half = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7, select_frac=0.5
    ).run()
    assert half.unselected > 0
    assert half.n_events < full.n_events
    assert half.merges < full.merges
    # unselected slots idle the device: nothing else may shift
    assert half.rate_limited == 0 and half.updates_dropped_wire == 0


def test_per_tier_rate_limit():
    spec = FleetSpec.synth(2000, seed=SEED)
    free = MegaFleet(spec, cluster_size=64, k=8, local_lr=0.7).run()
    limited = MegaFleet(
        spec, cluster_size=64, k=8, local_lr=0.7,
        rate_limit_regional=0.05, rate_limit_global=0.05,
    ).run()
    assert limited.rate_limited > 0
    assert limited.merges < free.merges
    assert limited.buffered < free.buffered


# ---- scale + structure smoke ----


def test_scale_smoke_20k():
    """A 20k-client hierarchical drive: structure invariants at a scale
    the heap cannot reach in test time (the 1M row lives in
    BENCH_ASYNC; this pins the same engine path at CI cost)."""
    spec = FleetSpec.synth(20_000, seed=SEED, slow_frac=0.1)
    res = MegaFleet(
        spec, cluster_size=512, k=32, updates_per_node=4, local_lr=0.7
    ).run()
    t, v, l = _curves(res)
    assert res.merges == res.version == v[-1]
    assert v == sorted(v) and len(set(v)) == len(v)
    assert np.all(np.diff(t) >= 0)  # mint times monotone
    assert l[-1] < l[0] * 0.05  # the fleet actually converges
    assert res.regional_merges > res.merges
    # every regional flush consumed exactly K=32 admitted contributions;
    # anything left over is an unflushed partial window per regional
    n_regionals = len(MegaFleet(spec, cluster_size=512, k=32).router.regionals)
    assert 32 * res.regional_merges <= res.buffered
    assert res.buffered < 32 * res.regional_merges + 32 * n_regionals
    assert res.clients_per_sec > 0


# ---- satellites: copy-on-write + the parity hook ----


def test_simfleet_copy_on_write_aliases_deliveries():
    """Pass-through sites alias: two edges that adopted the same global
    hold the SAME tree object (pre-CoW every delivery deep-copied), and
    the final result aliases the root buffer's params."""
    fleet = SimulatedAsyncFleet(
        8, seed=3, cluster_size=0, updates_per_node=3, local_lr=0.7
    )
    res = fleet.run()
    root = fleet.router.root
    edges = [
        a for a, n in fleet.nodes.items()
        if a != root and n.global_params is not None and n.known_version == res.version
    ]
    assert len(edges) >= 2
    first = fleet.nodes[edges[0]].global_params
    assert all(fleet.nodes[a].global_params is first for a in edges[1:])
    assert res.params is fleet._buffers[root]["global"].snapshot()[0]


def test_export_spec_matches_population():
    fleet = SimulatedAsyncFleet(
        32, seed=SEED, cluster_size=8, updates_per_node=2, slow_frac=0.25
    )
    spec = fleet.export_spec()
    addrs = sorted(fleet.nodes)
    assert spec["durations"].shape == (32,)
    for j, a in enumerate(addrs):
        assert spec["durations"][j] == fleet.nodes[a].duration
        assert spec["num_samples"][j] == fleet.nodes[a].num_samples
    np.testing.assert_array_equal(
        spec["targets"][5], fleet._target(fleet.nodes[addrs[5]].idx)
    )
    fleet._init = {"w": np.zeros(4, np.float32), "b": np.zeros(2, np.float32)}
    with pytest.raises(ValueError, match="consensus-task layout"):
        fleet.export_spec()

    custom = SimulatedAsyncFleet(
        8, seed=SEED, cluster_size=0, train_fn=lambda i, p, r: p
    )
    with pytest.raises(ValueError, match="no vectorized twin"):
        custom.export_spec()

    big = SimulatedAsyncFleet(10_001, seed=SEED, cluster_size=32)
    with pytest.raises(ValueError, match="4-digit address"):
        big.export_spec()
