"""Sharded nodes (ISSUE 10): partition-rule engine, submesh federation,
cross-slice aggregation.

Contracts pinned here:

- rule engine: first-match-wins, scalars replicate, unmatched paths loud;
- rule lint: dead rules / unknown axes / unmatched paths fail federation
  and learner construction at startup;
- ``federation_mesh`` never silently strands trailing devices;
- ``submesh_node_round`` at ``model_parallel=1`` is bit-identical to the
  overlay ``fused_node_round`` (params, opt state, accumulator);
- ``ShardedNodeFederation`` at ``model_parallel=1`` is bit-identical to
  ``SpmdFederation`` on a fixed seed; at ``model_parallel>1`` it matches
  to summation-order ulp while no device ever holds a full model
  (live-buffer bound + fold sharding specs);
- shard-wise fold vs restacked FedAvg numerical parity (bit-equal at
  equal weights, ulp otherwise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel.mesh import (
    federation_mesh,
    node_slices,
    submesh_federation_mesh,
)
from p2pfl_tpu.parallel.sharding import (
    check_partition_rules,
    lint_partition_rules,
    match_partition_rules,
    tree_shardings,
)
from p2pfl_tpu.settings import Settings

# the MLP's Megatron-style rule set: hidden dim column- then row-parallel
MLP_RULES = (
    (r"Dense_0/kernel", (None, "model")),
    (r"Dense_1/kernel", ("model", None)),
    (r"Dense_2/kernel", (None, "model")),
    (r".*", ()),
)


def _tree_bit_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# ---- rule engine ----


def test_match_rules_first_match_wins_and_scalars_replicate():
    tree = {
        "layer": {"attn": {"wq": {"kernel": jnp.zeros((4, 8))}}},
        "scale": jnp.zeros(()),  # scalar: always P() even though .* matches
    }
    rules = (
        (r"attn/(wq|wk)/kernel", (None, "model")),
        (r"kernel", ("model", None)),  # shadowed for wq — first match wins
        (r".*", ()),
    )
    specs = match_partition_rules(rules, tree)
    assert specs["layer"]["attn"]["wq"]["kernel"] == P(None, Settings.MESH_MODEL_AXIS)
    assert specs["scale"] == P()


def test_match_rules_unmatched_raises_and_replicate_mode():
    tree = {"odd_name": jnp.zeros((4, 4))}
    with pytest.raises(ValueError, match="no partition rule matches"):
        match_partition_rules(((r"kernel", (None, "model")),), tree)
    specs = match_partition_rules(
        ((r"kernel", (None, "model")),), tree, on_unmatched="replicate"
    )
    assert specs["odd_name"] == P()


def test_lint_reports_dead_rules_unknown_axes_unmatched():
    mesh = federation_mesh(devices=jax.devices()[:2])  # (nodes=2, model=1)
    tree = {
        "Dense_0": {"kernel": jnp.zeros((8, 4)), "bias": jnp.zeros((4,))},
        "odd": jnp.zeros((2, 2)),
    }
    rules = (
        (r"Dense_0/kernel", (None, "model")),
        (r"Dnse_0/kernel", ("model", None)),  # typo: never matches = dead
        (r"bias", ("bogus_axis",)),
    )
    report = lint_partition_rules(rules, tree, mesh)
    assert report.unmatched == ["odd"]
    assert report.dead_rules == [r"Dnse_0/kernel"]
    assert ("bias", "bogus_axis") in report.unknown_axes
    assert not report.ok()
    with pytest.raises(ValueError, match="fails lint"):
        check_partition_rules(rules, tree, mesh)


def test_lint_clean_set_and_indivisible_is_informational():
    mesh = node_slices(submesh_federation_mesh(1, 2, devices=jax.devices()[:2]))[0]
    tree = {"Dense_0": {"kernel": jnp.zeros((8, 6)), "bias": jnp.zeros((3,))}}
    rules = ((r"kernel", (None, "model")), (r".*", ()))
    report = lint_partition_rules(rules, tree, mesh)
    assert report.ok()
    # 6 % 2 == 0: divisible, nothing reported
    assert report.indivisible == []
    odd = {"Dense_0": {"kernel": jnp.zeros((8, 5)), "bias": jnp.zeros((3,))}}
    report2 = lint_partition_rules(rules, odd, mesh)
    assert report2.ok()  # indivisible is not an error…
    assert ("Dense_0/kernel", Settings.MESH_MODEL_AXIS) in report2.indivisible
    # …and placement replicates that leaf instead of failing
    sh = tree_shardings(mesh, odd, rules)
    assert sh["Dense_0"]["kernel"].spec == P(None, None)


def test_tree_shardings_raises_on_unknown_axis_and_scalar_rules_stay_live():
    # review regressions: (a) un-linted placement entry points must fail
    # loudly on an axis the mesh doesn't carry (the pre-engine
    # transformer_shardings raised KeyError; silent full replication is
    # the exact failure the engine exists to prevent); (b) a rule whose
    # only matches are size-1 leaves is live, not dead
    mesh = federation_mesh(devices=jax.devices()[:2])  # axes: nodes, model
    tree = {"w": jnp.zeros((4, 4))}
    with pytest.raises(ValueError, match="not in the mesh"):
        tree_shardings(mesh, tree, ((r"w", ("bogus_axis", None)),))
    scalars = {"scale": jnp.zeros((1,)), "w": jnp.zeros((8, 8))}
    rules = ((r"scale", ()), (r"w", ("model", None)))
    report = lint_partition_rules(rules, scalars, mesh)
    assert report.dead_rules == []
    check_partition_rules(rules, scalars, mesh)  # must not raise


def test_lint_tuple_axis_product_divisibility_matches_placement():
    # review regression: a dim sharded over a TUPLE of axes divides by the
    # PRODUCT of their sizes at placement — the lint must report the same
    # product-indivisible leaves, or a spec could lint clean while
    # silently replicating
    mesh = node_slices(submesh_federation_mesh(1, 2, 2, devices=jax.devices()[:4]))[0]
    tree = {"w": jnp.zeros((2, 4))}
    rules = ((r"w", (("data", "model"), None)),)
    report = lint_partition_rules(rules, tree, mesh)
    assert report.indivisible == [("w", "data+model")]
    assert tree_shardings(mesh, tree, rules)["w"].spec == P(None, None)
    # product-divisible: clean lint, sharded placement
    ok = {"w": jnp.zeros((4, 4))}
    assert lint_partition_rules(rules, ok, mesh).indivisible == []
    spec = tree_shardings(mesh, ok, rules)["w"].spec
    assert spec == P((Settings.MESH_DATA_AXIS, Settings.MESH_MODEL_AXIS), None)


def test_spmd_lm_default_mesh_folds_nodes_without_stranding():
    # review regression: SpmdLmFederation's default mesh passes the exact
    # device subset (n_nodes=2 x expert_parallel=2 on 8 devices used to
    # rely on federation_mesh's silent truncation, which now raises)
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLmFederation

    cfg = TransformerConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=1, ffn_hidden=64
    )
    data = FederatedDataset.synthetic_lm(
        vocab_size=64, seq_len=16, n_train=32, n_test=8
    )
    fed = SpmdLmFederation.from_dataset(
        tiny_transformer(seq_len=16, cfg=cfg), data, n_nodes=2,
        expert_parallel=2, batch_size=4, vote=False,
    )
    assert dict(fed.mesh.shape) == {
        Settings.MESH_NODES_AXIS: 2, Settings.MESH_MODEL_AXIS: 2
    }


def test_opt_state_places_by_the_same_rules():
    import optax

    mesh = node_slices(submesh_federation_mesh(1, 2, devices=jax.devices()[:2]))[0]
    params = {"Dense_0": {"kernel": jnp.ones((8, 4)), "bias": jnp.zeros((4,))}}
    tx = optax.adam(1e-3)
    opt_struct = jax.eval_shape(tx.init, params)
    sh = tree_shardings(mesh, opt_struct, MLP_RULES[:1] + ((r".*", ()),))
    placed = jax.jit(tx.init, out_shardings=sh)(
        jax.device_put(params, tree_shardings(mesh, params, MLP_RULES[:1] + ((r".*", ()),)))
    )
    mu_kernel = placed[0].mu["Dense_0"]["kernel"]
    assert mu_kernel.sharding.spec == P(None, Settings.MESH_MODEL_AXIS)
    # Adam's step counter is a scalar: replicated, never tripping the lint
    assert placed[0].count.sharding.spec == P()


# ---- mesh construction ----


def test_federation_mesh_raises_on_stranded_devices():
    devs = jax.devices()
    # n_nodes=3 over 8 devices used to silently build a 2-device mesh
    with pytest.raises(ValueError, match="strand"):
        federation_mesh(n_nodes=3, devices=devs)
    # the explicit-subset escape stays available and exact
    m = federation_mesh(n_nodes=3, devices=devs[:3])
    assert m.shape[Settings.MESH_NODES_AXIS] == 3
    # n_nodes >= slots still folds logical nodes onto all slots
    m2 = federation_mesh(n_nodes=64, devices=devs)
    assert m2.shape[Settings.MESH_NODES_AXIS] == len(devs)


def test_submesh_federation_mesh_and_node_slices():
    gm = submesh_federation_mesh(2, model_parallel=2, data_parallel=2)
    assert dict(gm.shape) == {
        Settings.MESH_NODES_AXIS: 2,
        Settings.MESH_DATA_AXIS: 2,
        Settings.MESH_MODEL_AXIS: 2,
    }
    slices = node_slices(gm)
    assert len(slices) == 2
    assert dict(slices[0].shape) == {
        Settings.MESH_DATA_AXIS: 2,
        Settings.MESH_MODEL_AXIS: 2,
    }
    # disjoint device ownership — the slices are independent dispatch targets
    d0 = set(np.asarray(slices[0].devices).flat)
    d1 = set(np.asarray(slices[1].devices).flat)
    assert not (d0 & d1)
    with pytest.raises(ValueError, match="exactly"):
        submesh_federation_mesh(3, model_parallel=3)  # 9 > 8 devices


# ---- node round bit-parity ----


def test_submesh_node_round_bit_identical_to_fused_node_round():
    from p2pfl_tpu.learning.learner import sgd
    from p2pfl_tpu.parallel.spmd import fused_node_round
    from p2pfl_tpu.parallel.submesh import submesh_node_round

    model = mlp(seed=0)
    tx = sgd(1e-2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(48, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 10, size=(48,)).astype(np.int32))
    perm = rng.permutation(48).reshape(1, 6, 8).repeat(2, axis=0).astype(np.int32)
    w = jnp.float32(64.0)

    def run_fused():
        p = jax.tree.map(jnp.asarray, model.params)
        o = tx.init(p)
        # the overlay path receives pre-gathered batches; the submesh path
        # gathers the SAME rows in-program — values identical by gather
        return fused_node_round(
            p, o, jnp.take(x, perm, axis=0), jnp.take(y, perm, axis=0), w,
            module=model.module, tx=tx,
        )

    def run_submesh():
        p = jax.tree.map(jnp.asarray, model.params)
        o = tx.init(p)
        return submesh_node_round(p, o, x, y, perm, w, module=model.module, tx=tx)

    a = run_fused()
    b = run_submesh()
    assert _tree_bit_equal(a["params"], b["params"])
    assert _tree_bit_equal(a["opt_state"], b["opt_state"])
    assert _tree_bit_equal(a["train_losses"], b["train_losses"])
    # the submesh variant's accumulator carries the stacking axis, value-equal
    assert _tree_bit_equal(
        a["psum"], jax.tree.map(lambda x: x[0], b["psum"])
    )
    assert np.asarray(b["wsum"]).shape == (1,)
    assert float(a["wsum"]) == float(b["wsum"][0])


# ---- federation parity ----


def _mk_feds(optimizer="sgd", model_parallel=1, keep_opt_state=False, n=4, vote=False):
    from p2pfl_tpu.parallel import ShardedNodeFederation, SpmdFederation

    data = FederatedDataset.synthetic_mnist(n_train=64 * n, n_test=32, seed=5)
    kw = dict(
        batch_size=16, vote=vote, seed=3, optimizer=optimizer,
        learning_rate=1e-2, keep_opt_state=keep_opt_state,
    )
    sharded = ShardedNodeFederation.from_dataset(
        mlp(seed=0), data, n_nodes=n, rules=MLP_RULES,
        model_parallel=model_parallel, **kw,
    )
    ref = SpmdFederation.from_dataset(mlp(seed=0), data, n_nodes=n, **kw)
    return sharded, ref


def test_sharded_federation_m1_bit_identical_to_spmd():
    sharded, ref = _mk_feds(optimizer="adam", keep_opt_state=True)
    for _ in range(3):
        sharded.run_round(epochs=1)
        ref.run_round(epochs=1)
    for i in range(sharded.n):
        assert _tree_bit_equal(
            sharded.node_params(i), jax.tree.map(lambda x, i=i: x[i], ref.params)
        )
        assert _tree_bit_equal(
            sharded.opt_state[i], jax.tree.map(lambda x, i=i: x[i], ref.opt_state)
        )
    # the round accumulator fold saw every node: total weight is the full
    # sample count (the [N] wsum vector is the retained introspection
    # record; the psum buffers themselves must not outlive the fold)
    assert float(jnp.sum(sharded.last_fold["wsum"])) == float(sum(sharded._sizes))


def test_sharded_federation_m1_vote_path_matches_spmd():
    # partial participation: non-elected nodes contribute explicit zero
    # accumulators — the same w=0 terms the SPMD masked reduce carries
    Settings.TRAIN_SET_SIZE = 3
    sharded, ref = _mk_feds(n=4, vote=True)
    for _ in range(2):
        e1 = sharded.run_round(epochs=1)
        e2 = ref.run_round(epochs=1)
    assert (sharded.train_mask == ref.train_mask).all()
    assert sharded.train_mask.sum() == 3.0
    # 3 of 4 elected: total weight 192 is no longer a power-of-two multiple
    # of each node's 64, so accumulate-then-divide vs normalize-then-
    # tensordot agree to summation-order ulp — the documented fold
    # numerics — not bit-for-bit (that contract holds at equal weights
    # whose normalization is exact, i.e. full participation). The second
    # round's training compounds the round-1 ulp, hence the looser bound.
    for x, y in zip(
        jax.tree.leaves(sharded.node_params(0)), jax.tree.leaves(ref.params)
    ):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y[0]), rtol=1e-3, atol=5e-6)
    assert np.isfinite(e1["train_loss"]) and np.isfinite(float(e2["train_loss"]))


def test_sharded_federation_m2_matches_single_chip_to_ulp():
    sharded, ref = _mk_feds(model_parallel=2)
    for _ in range(2):
        sharded.run_round(epochs=1)
        ref.run_round(epochs=1)
    for x, y in zip(jax.tree.leaves(sharded.node_params(0)), jax.tree.leaves(ref.params)):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y[0]), rtol=2e-5, atol=1e-6
        )


def test_sharded_federation_never_materializes_full_model_per_device():
    from p2pfl_tpu.parallel.submesh import per_device_bytes

    sharded, _ = _mk_feds(model_parallel=2)
    sharded.run_round(epochs=1)
    # fold inputs: every stacked accumulator leaf was sharded over nodes
    for sharding in jax.tree.leaves(
        sharded.last_fold["psum_shardings"],
        is_leaf=lambda x: hasattr(x, "spec"),
    ):
        assert sharding.spec[0] == Settings.MESH_NODES_AXIS
        assert not sharding.is_fully_replicated
    assert sharded.last_fold["wsum"].sharding.spec[0] == Settings.MESH_NODES_AXIS
    # fold outputs: the diffused aggregate stays model-sharded — the big
    # kernels' shards are half tensors, never the whole
    p0 = sharded.node_params(0)
    k0 = p0["Dense_0"]["kernel"]
    assert k0.sharding.spec == P(None, Settings.MESH_MODEL_AXIS)
    assert k0.addressable_shards[0].data.shape == (k0.shape[0], k0.shape[1] // 2)
    # live-buffer bound: no device holds a full params+opt copy
    full = sum(
        np.asarray(x).nbytes
        for x in jax.tree.leaves(sharded.model.params)
    ) * 2  # params + adam mu/nu would be 3x; sgd opt is empty — params alone
    per_dev = per_device_bytes(sharded.params, sharded.opt_state)
    assert max(per_dev.values()) < full / 2 * 1.2  # ~half + replicated slack


def test_fold_vs_restacked_fedavg_parity():
    from jax.sharding import NamedSharding

    from p2pfl_tpu.ops.aggregation import fedavg, fedavg_fold_stacked

    rng = np.random.default_rng(7)
    n = 4
    # node axis SHARDED like the real fold (and like SpmdFederation's
    # stacked reduce): both reductions then lower to the same per-shard
    # partial + all-reduce — the layout the bit-equality claim lives on
    mesh = federation_mesh(devices=jax.devices()[:n])
    shard = NamedSharding(mesh, P(Settings.MESH_NODES_AXIS))
    stacked = {
        "a": jax.device_put(rng.normal(size=(n, 6, 4)).astype(np.float32), shard),
        "b": jax.device_put(rng.normal(size=(n, 3)).astype(np.float32), shard),
    }
    ref_struct = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), stacked)

    def fold(weights):
        w = jax.device_put(np.asarray(weights, np.float32), shard)
        psum = jax.jit(
            lambda s, ws: jax.tree.map(
                lambda x: x * ws.reshape((n,) + (1,) * (x.ndim - 1)), s
            )
        )(stacked, w)
        return jax.jit(lambda p, ws: fedavg_fold_stacked(p, ws, ref_struct))(psum, w)

    # equal weights: scaling by the common factor commutes with every
    # rounding step — bit-identical to the restacked fedavg kernel
    eq = fold([32.0] * n)
    restacked_eq = fedavg(stacked, jax.device_put(np.full(n, 32.0, np.float32), shard))
    assert _tree_bit_equal(eq, restacked_eq)
    # unequal weights: accumulate-then-divide vs normalize-then-tensordot —
    # summation-order ulp, not bit-for-bit (the documented honest numerics)
    uneq_w = [31.0, 64.0, 17.0, 96.0]
    uneq = fold(uneq_w)
    restacked = fedavg(stacked, jax.device_put(np.asarray(uneq_w, np.float32), shard))
    for x, y in zip(jax.tree.leaves(uneq), jax.tree.leaves(restacked)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)


def test_sharded_federation_data_parallel_smoke():
    from p2pfl_tpu.parallel import ShardedNodeFederation

    data = FederatedDataset.synthetic_mnist(n_train=128, n_test=16, seed=5)
    fed = ShardedNodeFederation.from_dataset(
        mlp(seed=0), data, n_nodes=2, rules=MLP_RULES,
        model_parallel=2, data_parallel=2, batch_size=16, vote=False, seed=3,
    )
    assert len(set(np.asarray(fed.mesh.devices).flat)) == 8
    e = fed.run_round(epochs=1, eval=True)
    assert np.isfinite(e["train_loss"]) and 0.0 <= e["test_acc"] <= 1.0
    # diffusion: both nodes hold the identical aggregate
    assert _tree_bit_equal(fed.node_params(0), fed.node_params(1))


def test_sharded_federation_rejects_bad_rules_and_secagg():
    from p2pfl_tpu.parallel import ShardedNodeFederation

    data = FederatedDataset.synthetic_mnist(n_train=64, n_test=16, seed=5)
    with pytest.raises(ValueError, match="fails lint"):
        ShardedNodeFederation.from_dataset(
            mlp(seed=0), data, n_nodes=2,
            rules=((r"Dnse_0/kernel", (None, "model")), (r".*", ())),
            batch_size=16,
        )
    Settings.SECURE_AGGREGATION = True
    try:
        with pytest.raises(ValueError, match="trust domain"):
            ShardedNodeFederation.from_dataset(
                mlp(seed=0), data, n_nodes=2, rules=MLP_RULES, batch_size=16
            )
    finally:
        Settings.SECURE_AGGREGATION = False


def test_jax_learner_submesh_placement_matches_plain_learner():
    from p2pfl_tpu.learning.learner import JaxLearner

    data = FederatedDataset.synthetic_mnist(n_train=64, n_test=16, seed=1)
    gm = submesh_federation_mesh(1, model_parallel=2, devices=jax.devices()[:2])
    sm = node_slices(gm)[0]
    placed = JaxLearner(
        mlp(seed=0), data, batch_size=16, seed=9, mesh=sm, partition_rules=MLP_RULES
    )
    plain = JaxLearner(mlp(seed=0), data, batch_size=16, seed=9)
    # state placed per the rules, optimizer moments included
    k = placed.params["Dense_0"]["kernel"]
    assert k.sharding.spec == P(None, Settings.MESH_MODEL_AXIS)
    mu_k = placed.opt_state[0].mu["Dense_0"]["kernel"]
    assert mu_k.sharding.spec == P(None, Settings.MESH_MODEL_AXIS)
    placed.fit()
    plain.fit()
    for x, y in zip(jax.tree.leaves(placed.params), jax.tree.leaves(plain.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=2e-5, atol=1e-6)
    # the fused round runs sharded and its accumulator keeps the layout
    upd = placed.fused_round()
    assert upd is not None
    psum, _ = upd.partial_acc
    assert psum["Dense_0"]["kernel"].sharding.spec == P(None, Settings.MESH_MODEL_AXIS)
    # a typo'd rule set fails at learner construction
    with pytest.raises(ValueError, match="fails lint"):
        JaxLearner(
            mlp(seed=0), data, batch_size=16, mesh=sm,
            partition_rules=((r"Dnse/kernel", ("model",)),),
        )
