"""Streaming byte plane (ISSUE 19): chunked, pipelined encode→wire→decode.

Covers the P2TC chunk codec (`learning/weights.py`), the incremental
:class:`StreamDecoder`, the chunk-aware encode-once cache, the zero-copy
host encode, the memory transport's bounded-queue pump, and the gRPC
client-streaming path over real loopback sockets — including every
failure mode the ISSUE names: mid-stream receiver death (one failed send,
breaker feeds), a CRC-corrupt chunk (dropped loudly, node survives),
stream→unary fallback against a peer with streaming off, the >4 MB unary
regression (gRPC's default message cap), and a chaos federation
(drop+slow+crash) with streaming forced on.
"""

import threading
import time
import tracemalloc

import numpy as np
import pytest

from p2pfl_tpu.communication.faults import (
    CrashSpec,
    EdgeFault,
    FaultPlan,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
from p2pfl_tpu.communication.memory import InMemoryProtocol, MemoryRegistry
from p2pfl_tpu.communication.message import CommandResult, WeightsEnvelope
from p2pfl_tpu.learning import weights as W
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.learning.weights import (
    CHUNK_DATA,
    CHUNK_END,
    CHUNK_HEADER,
    DecodingParamsError,
    ModelUpdate,
    PayloadCache,
    StreamDecoder,
    chunk_encoded_payload,
    decode_params,
    encode_params,
    encode_params_chunked,
    estimate_payload_bytes,
    parse_stream_chunk,
    payload_from_chunks,
)
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    W.reset_wire_stats()
    yield
    MemoryRegistry.reset()
    Settings.WIRE_STREAM_ENABLED = True
    Settings.WIRE_STREAM_THRESHOLD = 8.0
    Settings.WIRE_CHUNK_MB = 2.0
    Settings.WIRE_STREAM_WINDOW = 4
    Settings.GRPC_MAX_MESSAGE_MB = 512
    Settings.MEMORY_WIRE_CODEC = False
    Settings.WIRE_COMPRESSION = "none"


def _tree(total_bytes: int = 1 << 20, leaves: int = 4, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    per = max(total_bytes // (4 * leaves), 1)
    return {
        f"layer{i}/w": rng.normal(size=per).astype(np.float32) for i in range(leaves)
    }


# ---------------------------------------------------------------------------
# chunk codec: framing + byte-compatibility with the unary frame
# ---------------------------------------------------------------------------


def test_chunk_bodies_concatenate_to_unary_payload():
    """THE byte-compat invariant: header+data chunk bodies == unary frame,
    whichever producer cut them (fresh encode or re-slice of cached bytes)."""
    tree = _tree(1 << 20)
    payload = encode_params(tree)
    for cb in (64 * 1024, 300_000, 1 << 22):
        chunks = chunk_encoded_payload(payload, cb)
        assert payload_from_chunks(chunks) == payload
        fresh = encode_params_chunked(tree, chunk_bytes=cb)
        assert payload_from_chunks(fresh) == payload
        # one decoder core: both the unary decoder and the stream decoder
        # accept the same bytes
        ref = decode_params(payload)
        dec = StreamDecoder()
        for c in chunks:
            dec.feed(c)
        assert dec.complete
        flat = dec.result_flat()
        assert set(flat) == set(ref)
        for k in ref:
            np.testing.assert_array_equal(np.asarray(flat[k]), ref[k])


def test_chunk_frames_are_self_delimiting_and_typed():
    tree = _tree(256 * 1024)
    chunks = encode_params_chunked(tree, chunk_bytes=64 * 1024)
    types = [parse_stream_chunk(c)[0] for c in chunks]
    assert types[0] == CHUNK_HEADER and types[-1] == CHUNK_END
    assert all(t == CHUNK_DATA for t in types[1:-1])
    seqs = [parse_stream_chunk(c)[1] for c in chunks]
    assert seqs == list(range(len(chunks)))


def test_cuts_are_leaf_aligned_when_leaves_fit():
    """Leaves smaller than a slab never straddle a chunk boundary — the
    receiver completes whole leaves per chunk."""
    tree = {f"l{i}": np.full(25_000, float(i), np.float32) for i in range(8)}  # 100 KB each
    payload = encode_params(tree)
    chunks = chunk_encoded_payload(payload, 256 * 1024)
    leaf_sizes = [100_000] * 8
    boundaries = {sum(leaf_sizes[: i + 1]) for i in range(8)}
    running = 0
    for c in chunks[1:-1]:
        running += len(parse_stream_chunk(c)[2])
        assert running in boundaries, f"cut at {running} straddles a leaf"


def test_oversized_leaf_is_split_across_chunks():
    tree = {"big": np.arange(1_000_000, dtype=np.float32)}  # 4 MB leaf
    chunks = encode_params_chunked(tree, chunk_bytes=256 * 1024)
    assert len(chunks) > 10  # header + ~16 data + end
    dec = StreamDecoder()
    for c in chunks:
        dec.feed(c)
    np.testing.assert_array_equal(
        np.asarray(dec.result_flat()["big"]), np.asarray(tree["big"])
    )


def test_parse_chunk_violations():
    (chunk,) = [c for c in encode_params_chunked(_tree(1024), chunk_bytes=1 << 20)
                if parse_stream_chunk(c)[0] == CHUNK_DATA][:1]
    with pytest.raises(DecodingParamsError, match="magic"):
        parse_stream_chunk(b"NOPE" + chunk[4:])
    with pytest.raises(DecodingParamsError, match="magic"):
        parse_stream_chunk(chunk[:8])  # shorter than the frame header
    with pytest.raises(DecodingParamsError, match="!= framed"):
        parse_stream_chunk(chunk[:-1])  # truncated body
    corrupt = bytearray(chunk)
    corrupt[-1] ^= 0xFF
    with pytest.raises(DecodingParamsError, match="CRC mismatch"):
        parse_stream_chunk(bytes(corrupt))
    # unknown type with a VALID body CRC must still be rejected
    from p2pfl_tpu import native
    import struct as _struct

    bad = bytearray(chunk)
    bad[4] = 7
    body = bytes(bad[17:])
    _struct.pack_into("<III", bad, 5, 1, len(body), native.crc32c(body, 0))
    with pytest.raises(DecodingParamsError, match="unknown chunk type"):
        parse_stream_chunk(bytes(bad))


# ---------------------------------------------------------------------------
# StreamDecoder: incremental decode + full failure algebra
# ---------------------------------------------------------------------------


def test_decoder_handles_scalar_empty_and_int8_leaves():
    tree = {
        "scalar": np.float32(3.5),
        "empty": np.zeros((0, 4), np.float32),
        "mat": np.linspace(-1, 1, 4096, dtype=np.float32).reshape(64, 64),
    }
    Settings.WIRE_COMPRESSION = "int8"
    try:
        chunks = encode_params_chunked(
            {k: np.asarray(v) for k, v in tree.items()}, compression="int8",
            chunk_bytes=64 * 1024,
        )
    finally:
        Settings.WIRE_COMPRESSION = "none"
    dec = StreamDecoder()
    for c in chunks:
        dec.feed(c)
    flat = dec.result_flat()
    assert flat["empty"].shape == (0, 4)
    ref = decode_params(payload_from_chunks(chunks))
    for k in ref:
        np.testing.assert_array_equal(np.asarray(flat[k]), ref[k])


def test_tk8_stream_reassembles_byte_identical_frame():
    """Delta-coded streams need the receiver's anchor at materialize time:
    the decoder hands back the byte-identical unary frame instead of eager
    leaves, and the normal anchored decode path takes over."""
    tree = _tree(512 * 1024)
    anchor = {k: v - 0.01 for k, v in tree.items()}
    payload = encode_params(tree, compression="topk8", anchor=anchor, anchor_tag="3:1")
    chunks = chunk_encoded_payload(payload, 64 * 1024)
    dec = StreamDecoder()
    for c in chunks:
        dec.feed(c)
    assert dec.complete and dec.reassembled
    assert dec.result_payload() == payload
    with pytest.raises(DecodingParamsError, match="result_payload"):
        dec.result_flat()
    # the reassembled frame decodes against the anchor like any unary one
    out = decode_params(dec.result_payload(), anchor=anchor, anchor_tag="3:1")
    assert set(out) == set(tree)


@pytest.mark.parametrize(
    "mutate, err",
    [
        (lambda ch: [ch[0], ch[0], *ch[1:]], "duplicate stream header|out-of-order"),
        (lambda ch: ch[1:], "out-of-order|data chunk before"),
        (lambda ch: [ch[0], *ch[2:]], "out-of-order"),
        (lambda ch: [*ch, ch[-1]], "chunk after end"),
        (lambda ch: [ch[0], ch[-1]], "out-of-order"),
        (lambda ch: ch[:-1] + [None], "incomplete-sentinel"),
    ],
)
def test_decoder_rejects_malformed_streams(mutate, err):
    chunks = encode_params_chunked(_tree(512 * 1024), chunk_bytes=64 * 1024)
    dec = StreamDecoder()
    seq = mutate(list(chunks))
    if seq[-1] is None:  # truncated stream: ended without the end chunk
        for c in seq[:-1]:
            dec.feed(c)
        assert not dec.complete
        with pytest.raises(DecodingParamsError, match="incomplete"):
            dec.result_flat()
        return
    with pytest.raises(DecodingParamsError, match=err):
        for c in seq:
            dec.feed(c)


def test_decoder_catches_end_chunk_lies():
    """A wrong declared chunk count or short byte total is a failed
    transfer even when every individual chunk verifies."""
    import json as _json
    import struct as _struct

    from p2pfl_tpu import native

    chunks = list(encode_params_chunked(_tree(512 * 1024), chunk_bytes=64 * 1024))

    def _end(n: int, seq: int) -> bytes:
        body = _json.dumps({"n": n}).encode()
        out = bytearray(17 + len(body))
        out[0:4] = b"P2TC"
        out[4] = CHUNK_END
        _struct.pack_into("<III", out, 5, seq, len(body), native.crc32c(body, 0))
        out[17:] = body
        return bytes(out)

    n_data = len(chunks) - 2
    dec = StreamDecoder()
    with pytest.raises(DecodingParamsError, match="chunk count mismatch"):
        for c in chunks[:-1] + [_end(n_data + 5, n_data + 1)]:
            dec.feed(c)
    # drop one data chunk and renumber the end so the count LOOKS right:
    # the running byte total vs the header's declared length exposes it
    dec = StreamDecoder()
    with pytest.raises(DecodingParamsError, match="stream truncated"):
        for c in chunks[:-2] + [_end(n_data, n_data)]:
            dec.feed(c)


def test_decoder_scratch_is_bounded_not_model_sized():
    """The MEASURED bounded-memory contract: a decoder that streamed an
    8 MB model through 128 KB chunks never buffered more than
    chunk + largest-leaf bytes — nowhere near the payload."""
    tree = _tree(8 << 20, leaves=16)  # 16 × 512 KB leaves
    chunk_bytes = 128 * 1024
    chunks = encode_params_chunked(tree, chunk_bytes=chunk_bytes)
    payload_bytes = sum(
        len(parse_stream_chunk(c)[2]) for c in chunks
        if parse_stream_chunk(c)[0] != CHUNK_END
    )
    dec = StreamDecoder()
    for c in chunks:
        dec.feed(c)
    largest_leaf = max(v.nbytes for v in tree.values())
    bound = 2 * chunk_bytes + largest_leaf + 4096
    assert 0 < dec.peak_scratch_bytes <= bound
    assert dec.peak_scratch_bytes < payload_bytes / 8
    assert W.wire_stats()["stream_peak_scratch_bytes"] == dec.peak_scratch_bytes


# ---------------------------------------------------------------------------
# estimate + encode-once cache (chunk-aware fan-out)
# ---------------------------------------------------------------------------


def test_estimate_payload_bytes():
    tree = _tree(1 << 20)
    u = ModelUpdate(tree, ["a"], 1)
    est = estimate_payload_bytes(u)
    real = len(encode_params(tree))
    assert abs(est - real) < 16 * 1024  # raw + header slack
    u.encoded = b"x" * 123
    assert estimate_payload_bytes(u) == 123  # exact once bytes exist
    assert estimate_payload_bytes(ModelUpdate(None, [], 1)) is None
    Settings.WIRE_COMPRESSION = "int8"
    try:
        u2 = ModelUpdate(tree, ["a"], 1)
        assert estimate_payload_bytes(u2) < real / 3
    finally:
        Settings.WIRE_COMPRESSION = "none"


def test_cache_fans_out_one_chunk_list_and_cross_reuses_unary():
    """encode-once/send-many: K streamed sends of one content share ONE
    chunk list; a later unary encode rebuilds from the cached chunks (and
    vice versa) instead of re-running the pipeline."""
    tree = _tree(1 << 20)
    cache = PayloadCache("fanout-node")

    u = ModelUpdate(tree, ["a"], 1)
    u.payload_cache = cache
    u.cache_version = 7
    u.cache_round = 0
    before = W.encode_call_count()
    first = u.encode_chunks()
    again = [ModelUpdate(tree, ["a"], 1) for _ in range(3)]
    for v in again:
        v.payload_cache, v.cache_version, v.cache_round = cache, 7, 0
    lists = [v.encode_chunks() for v in again]
    assert all(ls is first for ls in lists)
    assert W.encode_call_count() - before == 1  # pipeline ran once
    # cross-flavor: the unary encode reuses the cached chunk list bytes
    w2 = ModelUpdate(tree, ["a"], 1)
    w2.payload_cache, w2.cache_version, w2.cache_round = cache, 7, 0
    unary = w2.encode()
    assert W.encode_call_count() - before == 1  # STILL once
    assert unary == payload_from_chunks(first)
    # and the reverse direction: unary first, chunks re-sliced from it
    cache2 = PayloadCache("fanout-2")
    a = ModelUpdate(tree, ["a"], 1)
    a.payload_cache, a.cache_version, a.cache_round = cache2, 9, 0
    before = W.encode_call_count()
    pay = a.encode()
    b = ModelUpdate(tree, ["a"], 1)
    b.payload_cache, b.cache_version, b.cache_round = cache2, 9, 0
    assert payload_from_chunks(b.encode_chunks()) == pay
    assert W.encode_call_count() - before == 1


# ---------------------------------------------------------------------------
# zero-copy host encode (satellite: the double copy is gone)
# ---------------------------------------------------------------------------


def test_host_encode_buffers_are_zero_copy_views():
    from p2pfl_tpu.learning.weights import _encode_host

    tree = {"w": np.arange(1000, dtype=np.float32)}
    plans, _ = _encode_host(tree, None, {}, {}, None)
    for _, bufs in plans:
        for b in bufs:
            assert isinstance(b, memoryview)
    # the view aliases the source array's buffer (no per-leaf copy)
    tree["w"][0] = 123.0
    assert np.frombuffer(plans[0][1][0], np.float32)[0] == 123.0


def test_host_encode_allocates_payload_once_tracemalloc():
    """tracemalloc probe: peak transient allocation during a host encode is
    ~2× payload (the frame + the immutable bytes copy), not the old
    3× (per-leaf .tobytes() copies + frame + bytes)."""
    tree = _tree(8 << 20, leaves=8)
    payload_len = len(encode_params(tree))  # warm dtype/native paths
    tracemalloc.start()
    tracemalloc.reset_peak()
    encode_params(tree)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < payload_len * 2.6, (
        f"host encode peaked at {peak} bytes for a {payload_len}-byte payload "
        "— the per-leaf copy is back"
    )
    stats = W.wire_stats()
    assert stats["payload_bytes"] >= 2 * payload_len  # both encodes accounted
    assert stats["host_encodes"] >= 2


# ---------------------------------------------------------------------------
# memory transport: the bounded-queue pump
# ---------------------------------------------------------------------------


class _Sink:
    """Minimal weights command capturing delivered updates."""

    def __init__(self, name: str = "add_model") -> None:
        self.name = name
        self.received: list = []
        self.event = threading.Event()

    def get_name(self) -> str:
        return self.name

    def execute(self, source, round, *args, **kwargs):  # noqa: A002
        self.received.append(kwargs.get("update"))
        self.event.set()


def _mem_pair():
    a, b = InMemoryProtocol("s-a"), InMemoryProtocol("s-b")
    a.start()
    b.start()
    a.connect("s-b")
    sink = _Sink()
    b.add_command(sink)
    return a, b, sink


def test_memory_stream_pump_end_to_end():
    Settings.MEMORY_WIRE_CODEC = True
    Settings.WIRE_STREAM_THRESHOLD = 0.0
    Settings.WIRE_CHUNK_MB = 0.0  # clamps to the 64 KB floor: many chunks
    a, b, sink = _mem_pair()
    try:
        tree = _tree(1 << 20)
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["s-a"], 4))
        assert a.send("s-b", env)
        got = sink.received[0]
        assert got.decoded_flat is not None and got.encoded is None
        for k, v in tree.items():
            np.testing.assert_array_equal(np.asarray(got.decoded_flat[k]), v)
        assert got.contributors == ["s-a"] and got.num_samples == 4
        m = logger.get_comm_metrics("s-b")
        assert m["stream_recv"] == 1 and m["stream_recv_chunks"] > 3
    finally:
        a.stop()
        b.stop()


def test_memory_stream_window_is_bounded():
    """The pump's queue really backpressures: with a stalled consumer no
    more than WIRE_STREAM_WINDOW chunks are ever in flight."""
    Settings.MEMORY_WIRE_CODEC = True
    Settings.WIRE_STREAM_THRESHOLD = 0.0
    Settings.WIRE_CHUNK_MB = 0.0
    Settings.WIRE_STREAM_WINDOW = 2
    a, b, _sink = _mem_pair()
    max_seen = 0
    orig = InMemoryProtocol.handle_weights_stream

    def slow_stream(self, env, chunks):
        def throttled():
            nonlocal max_seen
            for c in chunks:
                time.sleep(0.01)  # let the producer run ahead if it can
                max_seen = max(max_seen, getattr(c, "__len__", lambda: 0)())
                yield c

        return orig(self, env, throttled())

    b.handle_weights_stream = slow_stream.__get__(b)
    try:
        tree = _tree(1 << 20)
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["s-a"], 1))
        assert a.send("s-b", env)
        # the queue object itself enforces the bound; verify the producer
        # finished (didn't deadlock) and chunks flowed
        assert logger.get_comm_metrics("s-b")["stream_recv_chunks"] > 4
    finally:
        a.stop()
        b.stop()


def test_memory_stream_crc_corruption_is_one_failed_send_node_survives():
    Settings.MEMORY_WIRE_CODEC = True
    Settings.WIRE_STREAM_THRESHOLD = 0.0
    a, b, sink = _mem_pair()
    orig = ModelUpdate.iter_chunks

    def corrupting(self, chunk_bytes=None):
        chunks = list(orig(self, chunk_bytes))
        bad = bytearray(chunks[1])
        bad[-1] ^= 0xFF
        chunks[1] = bytes(bad)
        return iter(chunks)

    ModelUpdate.iter_chunks = corrupting
    try:
        tree = _tree(256 * 1024)
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["s-a"], 1))
        assert not a.send("s-b", env)  # ONE failed send
        assert logger.get_comm_metrics("s-b")["stream_recv_drop"] == 1
        assert sink.received == []
    finally:
        ModelUpdate.iter_chunks = orig
    try:
        # the node survives: the next clean transfer goes through
        u = ModelUpdate(_tree(256 * 1024, seed=1), ["s-a"], 1)
        assert a.send("s-b", a.build_weights("add_model", 0, u))
        assert len(sink.received) == 1
    finally:
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# gRPC: real loopback sockets
# ---------------------------------------------------------------------------


def _grpc_pair():
    a, b = GrpcProtocol("127.0.0.1:0"), GrpcProtocol("127.0.0.1:0")
    a.start()
    b.start()
    assert a.connect(b.get_address())
    sink = _Sink()
    b.add_command(sink)
    return a, b, sink


def _stop_pair(a, b):
    a.stop()
    b.stop()


def test_grpc_unary_payload_above_4mb_regression():
    """A >4 MB unary weights payload crosses a real loopback socket — with
    gRPC's stock 4 MB default this fails RESOURCE_EXHAUSTED; the
    GRPC_MAX_MESSAGE_MB channel/server options fix it."""
    Settings.WIRE_STREAM_ENABLED = False  # force the unary path
    a, b, sink = _grpc_pair()
    try:
        tree = _tree(6 << 20)  # ~6 MB dense payload
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["x"], 1))
        assert len(env.update.encode()) > 4 * 1024 * 1024
        assert a.send(b.get_address(), env)
        got = sink.received[0]
        flat = decode_params(got.encoded)
        for k, v in tree.items():
            np.testing.assert_array_equal(flat[k], v)
        assert a.wire_stats["stream_sends"] == 0
    finally:
        _stop_pair(a, b)


def test_grpc_streamed_transfer_end_to_end():
    Settings.WIRE_STREAM_THRESHOLD = 1.0
    Settings.WIRE_CHUNK_MB = 1.0
    a, b, sink = _grpc_pair()
    try:
        tree = _tree(6 << 20)
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["x"], 3))
        assert a.send(b.get_address(), env)
        assert a.wire_stats["stream_sends"] == 1
        assert a.wire_stats["stream_chunks"] >= 6
        assert a.wire_stats["stream_fallback_unary"] == 0
        got = sink.received[0]
        assert got.decoded_flat is not None
        for k, v in tree.items():
            np.testing.assert_array_equal(np.asarray(got.decoded_flat[k]), v)
        m = logger.get_comm_metrics(b.get_address())
        assert m["stream_recv"] == 1
        # receiver never buffered anything model-sized: scratch is bounded
        # by one chunk plus the largest in-progress leaf, not the payload
        peak = W.wire_stats()["stream_peak_scratch_bytes"]
        largest_leaf = max(v.nbytes for v in tree.values())
        assert 0 < peak <= 2 * (1 << 20) + largest_leaf + 4096
        assert peak < len(env.update.encode()) / 2
    finally:
        _stop_pair(a, b)


def test_grpc_stream_to_unary_fallback_is_loud_and_sticky():
    """A peer with streaming off answers 'stream-unsupported': the SAME
    send falls back to unary (the transfer succeeds), the fallback counter
    fires, and later sends skip the stream probe for that peer."""
    Settings.WIRE_STREAM_THRESHOLD = 1.0
    a, b, sink = _grpc_pair()
    orig = GrpcProtocol.handle_weights_stream

    def rejecting(self, env, chunks):
        return CommandResult(ok=False, error="stream-unsupported")

    b.handle_weights_stream = rejecting.__get__(b)
    try:
        tree = _tree(2 << 20)
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["x"], 1))
        assert a.send(b.get_address(), env)  # fell back within the send
        assert a.wire_stats["stream_fallback_unary"] == 1
        assert a.wire_stats["stream_sends"] == 0
        assert sink.received and sink.received[0].encoded  # unary delivery
        # sticky: the second send goes straight to unary, no re-probe
        b.handle_weights_stream = orig.__get__(b)
        u = ModelUpdate(_tree(2 << 20, seed=1), ["x"], 1)
        assert a.send(b.get_address(), a.build_weights("add_model", 0, u))
        assert a.wire_stats["stream_fallback_unary"] == 1
        assert a.wire_stats["stream_sends"] == 0
    finally:
        _stop_pair(a, b)


def test_grpc_midstream_receiver_death_is_one_failed_send():
    """The receiver dies after consuming part of the stream: the sender
    sees exactly ONE failed send at the _do_send seam (no partial
    delivery), and the breaker records the failure."""
    Settings.WIRE_STREAM_THRESHOLD = 1.0
    Settings.WIRE_CHUNK_MB = 0.0  # 64 KB floor — many chunks in flight
    a, b, sink = _grpc_pair()

    def dying(self, env, chunks):
        it = iter(chunks)
        next(it)  # consume one chunk, then die mid-RPC
        raise RuntimeError("simulated hard crash")

    b.handle_weights_stream = dying.__get__(b)
    try:
        tree = _tree(4 << 20)
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["x"], 1))
        assert not a.send(b.get_address(), env)
        assert sink.received == []
        assert a.wire_stats["stream_sends"] == 0
        assert a.breaker._failures.get(b.get_address(), 0) >= 1
    finally:
        _stop_pair(a, b)


# ---------------------------------------------------------------------------
# chaos federation with streaming forced on
# ---------------------------------------------------------------------------


def test_chaos_federation_with_streaming_forced_on():
    """drop + slow peer + mid-round hard crash, every model payload
    streamed through the chunk pipeline: survivors converge, faults and
    breakers attribute per edge exactly as on the unary path."""
    Settings.MEMORY_WIRE_CODEC = True
    Settings.WIRE_STREAM_THRESHOLD = 0.0  # every payload streams
    Settings.WIRE_CHUNK_MB = 0.0
    n_nodes = 6
    Settings.TRAIN_SET_SIZE = n_nodes
    Settings.AGGREGATION_TIMEOUT = 60.0
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(n_nodes)]
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, n_nodes - 1, only_direct=True, wait=10)
    victim, slow = nodes[3], nodes[-1]
    plan = FaultPlan(
        seed=1905,
        default=EdgeFault(drop=0.05),
        slow_nodes={slow.addr: 0.2},
        crashes={victim.addr: CrashSpec(stage="TrainStage", round_no=0)},
    )
    install_fault_plan(nodes, plan)
    survivors = [n for n in nodes if n is not victim]
    try:
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(survivors, timeout=60)
        assert not victim._running
        for n in survivors:
            assert n.state.round is None

        def total(metric):
            return sum(
                m.get(metric, 0) for m in logger.get_comm_metrics().values()
            )

        # the pipeline actually carried the round
        assert total("stream_recv") > 0, "no payload streamed under forced streaming"
        assert total("stream_fallback_unary") == 0
        # fault/breaker attribution unchanged by streaming
        assert total("train_set_repair") >= 1
        assert total("breaker_open") >= 1
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
    finally:
        remove_fault_plan(nodes)
        for n in nodes:
            n.stop()
