"""Membership across experiments: back-to-back runs and late joiners.

The reference forbids joining DURING learning (``node.py:74-75,141-142``)
but the overlay outlives an experiment — a node that connects between
experiments must participate in the next one, and the same federation
must be able to run experiment after experiment without state bleed
(votes, aggregator windows, init latches all reset via ``state.clear``).
"""

import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import check_equal_models, full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


def _node(i, n, full):
    learner = JaxLearner(mlp(seed=i), full.partition(i, n), batch_size=64)
    node = Node(learner=learner)
    node.start()
    return node


def test_back_to_back_experiments():
    """The same federation runs two experiments; no state bleeds between."""
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = [_node(i, 2, full) for i in range(2)]
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)

    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=60)
    check_equal_models(nodes)
    assert all(n.state.experiment_epoch == 1 for n in nodes)

    # experiment 2 on the same overlay — from the OTHER node this time
    nodes[1].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=90)
    check_equal_models(nodes)
    assert all(n.state.experiment_epoch == 2 for n in nodes)
    assert nodes[0].learner.evaluate()["test_acc"] > 0.8
    for n in nodes:
        n.stop()


def test_late_joiner_participates_in_next_experiment():
    """A node that connects AFTER experiment 1 trains in experiment 2 and
    converges to the same model as the incumbents."""
    full = FederatedDataset.synthetic_mnist(n_train=1536, n_test=256)
    nodes = [_node(i, 3, full) for i in range(2)]
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=60)

    late = _node(2, 3, full)
    nodes.append(late)
    for n in nodes[:2]:
        full_connection(late, [n])
    wait_convergence(nodes, 2, only_direct=True)

    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes)
    assert late.state.experiment_epoch == 1  # its first experiment
    assert late.learner.evaluate()["test_acc"] > 0.8
    for n in nodes:
        n.stop()
