"""Test config: force an 8-device virtual CPU mesh BEFORE the backend initializes.

This replaces the reference's "multi-node without a cluster" approach
(real gRPC on loopback) with a virtual device mesh, per SURVEY.md §4.

NOTE: this environment pre-imports jax via a sitecustomize hook with
``JAX_PLATFORMS=axon`` (one real TPU chip behind a tunnel), so setting the
env var here is too late — ``jax.config.update`` still works as long as no
backend has been initialized yet. XLA_FLAGS is read at backend init, so
setting it here is still in time.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from p2pfl_tpu.settings import set_test_settings  # noqa: E402


@pytest.fixture(autouse=True)
def _fast_settings():
    set_test_settings()
    from p2pfl_tpu.management.logger import logger

    logger.set_level("DEBUG")
    yield


@pytest.fixture(autouse=True)
def _no_leaked_nodes():
    """Cross-test isolation: a test that fails before stopping its nodes
    must not leave live heartbeater/gossiper threads interfering with every
    test after it (observed: leaked gRPC heartbeaters evicting neighbors
    suite-wide). Stops leftovers and makes the leak visible."""
    yield
    from p2pfl_tpu.node import stop_leaked_nodes

    leaked = stop_leaked_nodes()
    if leaked:
        import warnings

        warnings.warn(f"test leaked running nodes (now stopped): {leaked}", stacklevel=1)
