"""Test config: force an 8-device virtual CPU mesh BEFORE jax initializes.

This replaces the reference's "multi-node without a cluster" approach
(real gRPC on loopback) with a virtual device mesh, per SURVEY.md §4.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

from p2pfl_tpu.settings import set_test_settings  # noqa: E402


@pytest.fixture(autouse=True)
def _fast_settings():
    set_test_settings()
    yield
