"""Profiling helpers, stage factory, ResNet smoke, 16-node overlay scale."""

import glob
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_low_latency_profile_preserves_semantic_knobs():
    """The documented low-latency profile only shrinks clocks; semantic
    knobs (train-set size, TTL, stall-exit tick count, vote formula) stay
    untouched so round outcomes match the defaults."""
    from p2pfl_tpu.settings import Settings, set_low_latency_settings

    semantic_before = (
        Settings.TRAIN_SET_SIZE,
        Settings.TTL,
        Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS,
        Settings.VOTE_EVERY_ROUND,
        Settings.WIRE_COMPRESSION,
    )
    set_low_latency_settings()
    try:
        assert (
            Settings.TRAIN_SET_SIZE,
            Settings.TTL,
            Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS,
            Settings.VOTE_EVERY_ROUND,
            Settings.WIRE_COMPRESSION,
        ) == semantic_before
        assert Settings.GOSSIP_MODELS_PERIOD <= 0.1
        assert Settings.HEARTBEAT_PERIOD <= 0.5
        assert Settings.VOTE_TIMEOUT < 60.0
    finally:
        from p2pfl_tpu.settings import set_test_settings

        set_test_settings()


def test_stopwatch_sections():
    from p2pfl_tpu.management.profiling import Stopwatch

    sw = Stopwatch()
    with sw.section("a"):
        time.sleep(0.01)
    with sw.section("a"):
        time.sleep(0.01)
    s = sw.summary()
    assert s["a"]["calls"] == 2 and s["a"]["total_s"] >= 0.02


def test_profiler_trace_writes_files(tmp_path):
    from p2pfl_tpu.management.profiling import annotate, trace

    d = str(tmp_path / "trace")
    with trace(d):
        with annotate("matmul", step=1):
            x = jnp.ones((64, 64))
            jax.block_until_ready(x @ x)
    assert glob.glob(d + "/**/*.pb", recursive=True) or glob.glob(
        d + "/**/*.json.gz", recursive=True
    )


def test_stage_factory():
    from p2pfl_tpu.stages.stage_factory import StageFactory
    from p2pfl_tpu.stages.learning_stages import TrainStage

    assert StageFactory.get_stage("TrainStage") is TrainStage
    with pytest.raises(KeyError):
        StageFactory.get_stage("NoSuchStage")


@pytest.mark.slow
def test_resnet_forward_and_grad():
    from p2pfl_tpu.models import resnet18

    model = resnet18()
    x = jnp.ones((2, 32, 32, 3))
    logits = model.apply(model.params, x)
    assert logits.shape == (2, 10)

    def loss(p):
        return jnp.sum(model.module.apply({"params": p}, x) ** 2)

    g = jax.grad(loss)(model.params)
    assert np.isfinite(float(jax.tree.leaves(g)[0].sum()))


def test_sixteen_node_overlay():
    """Overlay scale: 16 nodes, partial topology, full federation round."""
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning.learner import DummyLearner
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings
    from p2pfl_tpu.utils import wait_convergence, wait_to_finish, check_equal_models

    MemoryRegistry.reset()
    Settings.TRAIN_SET_SIZE = 4
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(16)]
    for n in nodes:
        n.start()
    # ring + chords topology (not full mesh): discovery must flood
    for i, n in enumerate(nodes):
        n.connect(nodes[(i + 1) % 16].addr)
        if i % 4 == 0:
            n.connect(nodes[(i + 7) % 16].addr)
    wait_convergence(nodes, 15, only_direct=False, wait=15)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=90)
    check_equal_models(nodes, atol=1e-6)
    for n in nodes:
        n.stop()
    MemoryRegistry.reset()
