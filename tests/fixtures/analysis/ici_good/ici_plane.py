"""Teeth fixture: the shipped ICI-plane shape — device-side mechanics only.

Zero-copy metadata assembly, D2D re-placement and H2D filler uploads are
all allowed inside the ``no-host-gather`` scope; this file MUST pass.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pair_mesh(src_mesh, dst_mesh):
    return Mesh(
        np.stack([src_mesh.devices, dst_mesh.devices]),
        ("ici_pair", *src_mesh.axis_names),
    )


def pair_global(leaf_src, leaf_fill, gsharding):
    gshape = (2,) + tuple(leaf_src.shape)
    dmap = {}
    for s in leaf_src.addressable_shards:
        dmap[s.device] = s.data.reshape((1,) + s.data.shape)
    for s in leaf_fill.addressable_shards:
        dmap[s.device] = s.data.reshape((1,) + s.data.shape)
    arrs = [dmap[d] for d in gsharding.addressable_devices_indices_map(gshape)]
    return jax.make_array_from_single_device_arrays(gshape, gsharding, arrs)


def filler(leaf, mesh):
    # H2D upload of zeros is fine — the contract is about payload D2H
    return jax.device_put(jnp.zeros(tuple(leaf.shape), leaf.dtype), NamedSharding(mesh, P()))


def payload_bytes(tree_leaves):
    # metadata-only accounting: shapes/dtypes, never the buffers
    return sum(x.size * x.dtype.itemsize for x in tree_leaves)
