# teeth: the shipped PR-2 fix shape — every knob is an explicit argument
# (KernelConfig idiom): static_argnames participate in the jit cache key,
# so changing a knob provably re-traces; dtypes ride in as arguments and
# reductions stay on device.
# MUST pass: jit-staleness

from functools import partial

import jax
import jax.numpy as jnp

_BLOCK = 128  # single-assignment module constant: static, fine to read


@partial(jax.jit, static_argnames=("mode", "agg_dtype"))
def flash_bwd(q, k, v, *, mode="flash", agg_dtype=jnp.float32):
    acc = q.astype(agg_dtype)
    if mode == "flash":
        return acc
    return k


def _kernel(x_ref, o_ref, *, scale):
    o_ref[...] = x_ref[...] * scale * _BLOCK


def apply(x, pl=None):
    kernel = partial(_kernel, scale=2.0)
    return pl.pallas_call(kernel, out_shape=x)(x)
