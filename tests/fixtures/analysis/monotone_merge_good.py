# teeth: the shipped PR-5 fix shape — every lattice merge is a monotone
# union/max read-merge-write serialized under status_merge_lock; whole-
# attribute replacement stays allowed (the replace-don't-mutate idiom of
# NodeState.increase_round / clear).
# MUST pass: monotone-merge


class ModelsAggregatedCommand:
    def execute(self, source, round, *args):
        st = self._state
        coverage = st.models_aggregated
        if st.round is None or round != st.round:
            return
        with st.status_merge_lock:
            prev = coverage.get(source)
            coverage[source] = sorted(set(prev) | set(args)) if prev else list(args)


class ModelsReadyCommand:
    def execute(self, source, round, *args):
        st = self._state
        with st.status_merge_lock:
            st.nei_status[source] = max(st.nei_status.get(source, -1), round)


class AsyncDoneCommand:
    def execute(self, source, round, *args):
        with self._state.status_merge_lock:
            self._state.async_done_peers.add(source)


class NodeState:
    def increase_round(self):
        self.round += 1
        self.models_aggregated = {}  # replacement, not mutation: allowed
