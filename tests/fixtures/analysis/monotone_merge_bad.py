# teeth: the PR-5 round-0 wedge shape. An unlocked overwrite of the
# coverage dict lets a stale redelivery clobber a newer view — the
# partial-gossip convergence detector reopens and the round wedges.
# MUST flag: monotone-merge


class ModelsAggregatedCommand:
    def execute(self, source, round, *args):
        st = self._state
        coverage = st.models_aggregated
        if st.round is None or round != st.round:
            return
        # overwrite, no lock: loses a sender's FINAL announcement when two
        # handler threads interleave their read-merge-writes
        coverage[source] = list(args)


class ModelsReadyCommand:
    def execute(self, source, round, *args):
        st = self._state
        st.nei_status[source] = round  # regression on stale redelivery, unlocked


class AsyncDoneCommand:
    def execute(self, source, round, *args):
        self._state.async_done_peers.add(source)  # unlocked set mutation
