# teeth: the PR-2 BWD_MODE staleness shape. A module global read at trace
# time participates in no jit cache key — flipping it keeps serving the
# OLD compiled program. Settings.* reads inside jit are the same trap,
# and host syncs on traced values break the no-host-sync dispatch
# contract of the fused-round programs.
# MUST flag: jit-staleness (x4)

from functools import partial

import jax
import numpy as np

from p2pfl_tpu.settings import Settings

BWD_MODE = "flash"


def set_bwd_mode(mode):
    global BWD_MODE
    BWD_MODE = mode


@jax.jit
def flash_bwd(q, k, v):
    if BWD_MODE == "flash":  # mutable global inside jit: stale after set_bwd_mode
        return q
    return k


@partial(jax.jit, static_argnames=("n",))
def fold(x, n):
    acc = x.astype(Settings.AGG_DTYPE)  # Settings read baked at first trace
    total = float(acc.sum())  # host sync on a traced value
    return np.asarray(total)  # host materialization inside jit


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * (2.0 if BWD_MODE == "flash" else 1.0)


def apply(x, pl=None):
    kernel = partial(_kernel)
    return pl.pallas_call(kernel, out_shape=x)(x)
