# teeth: the sharded-engine donation shape. The fleet program donates
# its sharded carry through partial(jax.jit, donate_argnums=…) wrapped
# AROUND shard_map — the donation declaration lives on the inner
# partial call, and a later read of the donated buffer without a rebind
# is the same "array has been deleted" poisoning as the plain-jit case.
# MUST flag: donation-reuse

from functools import partial

import jax
from jax.sharding import PartitionSpec

from p2pfl_tpu.parallel.compat import shard_map


def _body(w, events):
    return w, events.sum()


fleet_step = partial(jax.jit, donate_argnums=(0,))(
    shard_map(
        _body,
        mesh=None,
        in_specs=(PartitionSpec("clients"), PartitionSpec()),
        out_specs=(PartitionSpec("clients"), PartitionSpec()),
    )
)


class Driver:
    def run(self, events):
        out, total = fleet_step(self.w, events)
        return self.w.sum() + total  # self.w was donated: dead buffer
