# teeth: the shipped PR-9 fix shape — handlers compute under locks,
# collect action tuples, and send OUTSIDE every lock (the deadlock
# contract in federation/workflow.py AsyncContext docs).
# MUST pass: send-under-lock


class AsyncUpdateHandler:
    def execute(self, source, update):
        ctx = self.node.async_ctx
        with ctx.lock:
            res = ctx.rbuf.offer(update)
            actions = [("async_update", ctx.router.root, res)] if res else []
        for cmd, target, upd in actions:
            self.node.protocol.send(target, self.build(cmd, upd))

    def repair(self, addr):
        st = self.node.state
        with st.status_merge_lock:
            st.async_done_peers.add(addr)
        self.node.protocol.broadcast(self.node.protocol.build_msg("async_done"))

    def deferred_is_fine(self):
        # a closure DEFINED under a lock runs later, outside it — the
        # eviction-repair daemon-thread pattern in node.py
        with self.ctx.lock:
            def _repair():
                self.node.protocol.send(self.target, self.env)
            self.spawn(_repair)
