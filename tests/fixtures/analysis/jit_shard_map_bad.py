# teeth: the sharded-engine staleness shape. A shard_map body is a
# traced device program exactly like a jit body — a Settings read or a
# mutable-global read inside one bakes the first-trace value into every
# later call, and the decorator form (@partial(shard_map, …)) must not
# hide the body from the rule.
# MUST flag: jit-staleness (x3)

from functools import partial

import jax
import numpy as np
from jax.sharding import PartitionSpec

from p2pfl_tpu.parallel.compat import shard_map
from p2pfl_tpu.settings import Settings

CHUNK_OVERRIDE = 0


def set_chunk(c):
    global CHUNK_OVERRIDE
    CHUNK_OVERRIDE = c


@partial(
    shard_map,
    mesh=None,
    in_specs=(PartitionSpec("clients"),),
    out_specs=PartitionSpec("clients"),
)
def shard_body(w):
    # decorator form: Settings read inside the per-shard program
    return w * Settings.FEDBUFF_ALPHA


def build(mesh):
    def body(w):
        k = CHUNK_OVERRIDE  # mutable global inside the shard program
        total = np.asarray(w)  # host materialization of a traced value
        return w * k + total.sum()

    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(PartitionSpec("clients"),),
            out_specs=PartitionSpec("clients"),
        )
    )
