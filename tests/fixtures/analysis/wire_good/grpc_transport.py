# teeth: the shipped optional-key pattern — is-not-None guarded encode,
# .get() decode (absent frames decode unchanged), helper indirection for
# the trace context exactly like the real codec.
# MUST pass: wire-header-compat

import json


def encode_message(msg):
    d = {"src": msg.source, "cmd": msg.cmd, "args": list(msg.args)}
    if msg.trace_ctx is not None:
        d["tc"] = list(msg.trace_ctx)
    if msg.xp is not None:
        d["xp"] = msg.xp
    return json.dumps(d).encode()


def decode_message(data):
    d = json.loads(data.decode())
    return Message(d["src"], d["cmd"], trace_ctx=_trace_ctx(d), xp=d.get("xp"))


def _trace_ctx(d):
    tc = d.get("tc")
    return (str(tc[0]), str(tc[1])) if tc else None


def encode_weights(env):
    d = {"src": env.source, "round": env.round, "cmd": env.cmd}
    if env.trace_ctx is not None:
        d["tc"] = list(env.trace_ctx)
    if env.update.version is not None:
        d["vv"] = list(env.update.version)
    xp = env.xp or env.update.xp
    if xp is not None:
        d["xp"] = xp
    if env.update.sp is not None:
        d["sp"] = [list(env.update.sp[0]), env.update.sp[1], env.update.sp[2]]
    return json.dumps(d).encode()


def _sp_header(d):
    sp = d.get("sp")
    return (tuple(sp[0]), int(sp[1]), str(sp[2])) if sp else None


def decode_weights(data):
    d = json.loads(data.decode())
    vv = d.get("vv")
    return WeightsEnvelope(
        d["src"], d["round"], d["cmd"], version=vv, trace_ctx=_trace_ctx(d),
        xp=d.get("xp"), sp=_sp_header(d),
    )
