# teeth: the shipped protobuf interop codec shape — exactly the
# reference's Weights schema fields, no optional envelope keys.
# MUST pass: wire-header-compat


def encode_weights_pb(env):
    return pb.Weights(
        source=env.source,
        round=env.round,
        weights=env.update.encode(),
        contributors=list(env.update.contributors),
        weight=int(env.update.num_samples),
        cmd=env.cmd,
    ).SerializeToString()


def decode_weights_pb(data):
    w = pb.Weights.FromString(data)
    update = ModelUpdate(
        params=None,
        contributors=list(w.contributors),
        num_samples=int(w.weight),
        encoded=bytes(w.weights),
    )
    return WeightsEnvelope(w.source, w.round, w.cmd, update)
