# teeth: the shipped byte-path re-wrap — version, xp and trace_ctx all
# ride the rebuilt update/envelope, so MEMORY_WIRE_CODEC simulations see
# exactly what the network transports would deliver.
# MUST pass: wire-header-compat


class InMemoryProtocol:
    def _send_to_neighbor(self, nei, env, create_connection=False):
        peer = MemoryRegistry.get(nei)
        if Settings.MEMORY_WIRE_CODEC and env.update.params is not None:
            wire = ModelUpdate(
                params=None,
                contributors=list(env.update.contributors),
                num_samples=env.update.num_samples,
                encoded=env.update.encode(),
                version=env.update.version,
                xp=env.update.xp,
                sp=env.update.sp,
            )
            env = WeightsEnvelope(
                env.source, env.round, env.cmd, wire, env.msg_id,
                trace_ctx=env.trace_ctx, xp=env.xp,
            )
        return peer.handle_weights(env).ok
