# teeth: the shipped fix shape for the sharded-engine donation — the
# donated carry is rebound from the program's result before any later
# read, so a failed dispatch can recover and a successful one never
# touches the dead buffer.
# MUST pass: donation-reuse

from functools import partial

import jax
from jax.sharding import PartitionSpec

from p2pfl_tpu.parallel.compat import shard_map


def _body(w, events):
    return w, events.sum()


fleet_step = partial(jax.jit, donate_argnums=(0,))(
    shard_map(
        _body,
        mesh=None,
        in_specs=(PartitionSpec("clients"), PartitionSpec()),
        out_specs=(PartitionSpec("clients"), PartitionSpec()),
    )
)


class Driver:
    def run(self, events):
        self.w, total = fleet_step(self.w, events)  # rebind-on-return
        return self.w.sum() + total
