# teeth: the byte-path re-wrap drops the version triple and experiment
# identity — MEMORY_WIRE_CODEC simulations silently diverge from the
# network transports (dedup and xp filtering never see the fields).
# MUST flag: wire-header-compat


class InMemoryProtocol:
    def _send_to_neighbor(self, nei, env, create_connection=False):
        peer = MemoryRegistry.get(nei)
        if Settings.MEMORY_WIRE_CODEC and env.update.params is not None:
            wire = ModelUpdate(
                params=None,
                contributors=list(env.update.contributors),
                num_samples=env.update.num_samples,
                encoded=env.update.encode(),
                # version= and xp= NOT copied
            )
            env = WeightsEnvelope(env.source, env.round, env.cmd, wire, env.msg_id)
            # trace_ctx= and xp= NOT copied
        return peer.handle_weights(env).ok
