# teeth: every way the tc/vv/xp optional-key contract historically broke
# in the envelope codec — unconditional serialization (None hits the
# wire) and [] decode (KeyError on pre-key frames).
# MUST flag: wire-header-compat

import json


def encode_message(msg):
    d = {"src": msg.source, "cmd": msg.cmd, "args": list(msg.args)}
    if msg.trace_ctx is not None:
        d["tc"] = list(msg.trace_ctx)
    d["xp"] = msg.xp  # unconditional: old receivers now see "xp": null
    return json.dumps(d).encode()


def decode_message(data):
    d = json.loads(data.decode())
    # [] read: a frame from a pre-xp sender raises KeyError here
    return Message(d["src"], d["cmd"], trace_ctx=_trace_ctx(d), xp=d["xp"])


def _trace_ctx(d):
    tc = d.get("tc")
    return (str(tc[0]), str(tc[1])) if tc else None


def encode_weights(env):
    d = {"src": env.source, "round": env.round, "cmd": env.cmd}
    if env.trace_ctx is not None:
        d["tc"] = list(env.trace_ctx)
    if env.update.version is not None:
        d["vv"] = list(env.update.version)
    if env.xp is not None:
        d["xp"] = env.xp
    return json.dumps(d).encode()


def decode_weights(data):
    d = json.loads(data.decode())
    vv = d.get("vv")
    return WeightsEnvelope(
        d["src"], d["round"], d["cmd"], version=vv, trace_ctx=_trace_ctx(d), xp=d.get("xp")
    )
