# teeth: an optional envelope key leaking into the protobuf interop
# schema — the reference's generated stubs would reject / misparse the
# frame, breaking byte-compat with real reference nodes.
# MUST flag: wire-header-compat


def encode_weights_pb(env):
    out = pb.Weights(
        source=env.source,
        round=env.round,
        weights=env.update.encode(),
        contributors=list(env.update.contributors),
        weight=int(env.update.num_samples),
        cmd=env.cmd,
    )
    if env.update.version is not None:
        out.vv = list(env.update.version)  # schema leak
    return out.SerializeToString()
