# teeth: the shipped sharded-engine shape — every knob reaches the
# shard_map body as an explicit argument (the static FleetConfig
# contract), module constants are single-assignment, and host
# materialization happens OUTSIDE the traced program.
# MUST pass: jit-staleness

from functools import partial

import jax
import numpy as np
from jax.sharding import PartitionSpec

from p2pfl_tpu.parallel.compat import shard_map

SCALE = 2.0  # single-assignment module constant: static, fine


@partial(
    shard_map,
    mesh=None,
    in_specs=(PartitionSpec("clients"), PartitionSpec()),
    out_specs=PartitionSpec("clients"),
)
def shard_body(w, alpha):
    return w * alpha * SCALE


def build(mesh, chunk):
    def body(w):
        return w[:chunk] if chunk else w  # closure over a static python int

    program = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(PartitionSpec("clients"),),
            out_specs=PartitionSpec("clients"),
        )
    )

    def run(w):
        out = program(w)
        return np.asarray(out)  # host sync AFTER dispatch: allowed

    return run
