# teeth: the PR-6 donation-reuse shape. spmd_round donates params/opt
# state; a dispatch that dies after consuming the buffers leaves
# self.params deleted, and the later read poisons every following round
# with "array has been deleted" deep inside jit argument processing.
# MUST flag: donation-reuse

from functools import partial

import jax

_DONATED_STATE = ("c_global", "c_local")


@partial(jax.jit, static_argnames=("module",), donate_argnums=(0, 1), donate_argnames=_DONATED_STATE)
def spmd_round(stacked_params, opt_states, x_all, *, c_global=None, c_local=None, module=None):
    return stacked_params, opt_states


class Federation:
    def run_round(self):
        try:
            result = spmd_round(
                self.params, self.opt_state, self.x_all,
                c_global=self.c_global, c_local=self.c_local, module=self.module,
            )
        except Exception:
            pass  # no recovery: the donated buffers may already be consumed
        loss = result[2]
        # read of a possibly-deleted donated buffer — the historical bug
        return self.encode(self.params), loss
