"""Teeth fixture: the ICI weights plane quietly touching the host.

Every call here is a real way the zero-host-bytes contract has been (or
could be) silently broken — the basename puts this file in the
``no-host-gather`` scope, so each one MUST flag.
"""

import jax
import numpy as np


def shard_transfer(tree_leaves):
    # "just a shape check" that gathers the whole leaf host-side
    host = [np.asarray(x) for x in tree_leaves]
    return host


def digest(leaf):
    # byte materialization — the byte codec sneaking back into the plane
    return leaf.tobytes()


def debug_peek(leaf):
    val = jax.device_get(leaf)
    return val.item()


def rewrap(buf):
    return np.frombuffer(buf, dtype=np.int8)
