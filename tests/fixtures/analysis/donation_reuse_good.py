# teeth: the shipped PR-6 fix shape — rebind the donated state from the
# result on success, and recover (drop + rebuild) on dispatch failure
# before re-raising (_recover_donated_state in parallel/spmd.py).
# MUST pass: donation-reuse

from functools import partial

import jax

_DONATED_STATE = ("c_global", "c_local")


@partial(jax.jit, static_argnames=("module",), donate_argnums=(0, 1), donate_argnames=_DONATED_STATE)
def spmd_round(stacked_params, opt_states, x_all, *, c_global=None, c_local=None, module=None):
    return stacked_params, opt_states


class Federation:
    def run_round(self):
        try:
            result = spmd_round(
                self.params, self.opt_state, self.x_all,
                c_global=self.c_global, c_local=self.c_local, module=self.module,
            )
        except Exception:
            self._recover_donated_state()
            raise
        self.params, self.opt_state, loss = result[:3]
        self.c_global, self.c_local = result[3:5]
        return self.encode(self.params), loss
