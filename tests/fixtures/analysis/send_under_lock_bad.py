# teeth: the PR-9 lock-across-send shape. A command handler sending while
# holding the context lock re-enters the receiver's handler synchronously
# on the in-memory transport — two nodes deadlock on each other's locks.
# MUST flag: send-under-lock


class AsyncUpdateHandler:
    def execute(self, source, update):
        ctx = self.node.async_ctx
        with ctx.lock:
            res = ctx.rbuf.offer(update)
            if res:
                # sending with ctx.lock held: the receiver's handler takes
                # ITS context lock and may push back at us
                self.node.protocol.send(ctx.router.root, self.build(res))

    def repair(self, addr):
        st = self.node.state
        with st.status_merge_lock:
            st.async_done_peers.add(addr)
            self.node.protocol.broadcast(self.node.protocol.build_msg("async_done"))
