"""Regenerate the committed IDX fixture (tests/fixtures/idx/*.gz).

MNIST-format IDX files, deterministically generated and tiny (8×8 uint8
images, 128 train / 32 test, gzipped to a few KB total) so the
``FederatedDataset.from_idx`` loader — the first code path a real-data
user hits — has an executable witness in CI without any download egress.
The images are class prototypes + noise (the synthetic_mnist recipe,
quantized to uint8), so a federated round on them actually learns.

Run from the repo root: ``python tests/fixtures/generate_idx.py``
"""

import gzip
import os
import struct

import numpy as np

OUT = os.path.join(os.path.dirname(__file__), "idx")
SHAPE = (8, 8)
N_TRAIN, N_TEST, CLASSES, SEED = 128, 32, 10, 31


def _idx_bytes(a: np.ndarray) -> bytes:
    dtype_code = {np.uint8: 8}[a.dtype.type]
    header = struct.pack(">HBB", 0, dtype_code, a.ndim)
    header += struct.pack(f">{a.ndim}I", *a.shape)
    return header + a.tobytes()


def _make(n: int, split_seed: int, protos: np.ndarray):
    r = np.random.default_rng(SEED + split_seed)
    y = r.integers(0, CLASSES, size=n)
    x = protos[y] + r.normal(0.0, 0.35, size=(n, SHAPE[0] * SHAPE[1]))
    x = 1.0 / (1.0 + np.exp(-x))
    return (x.reshape((n, *SHAPE)) * 255).astype(np.uint8), y.astype(np.uint8)


def main() -> None:
    os.makedirs(OUT, exist_ok=True)
    rng = np.random.default_rng(SEED)
    protos = rng.normal(0.0, 1.5, size=(CLASSES, SHAPE[0] * SHAPE[1]))
    x_tr, y_tr = _make(N_TRAIN, 1, protos)
    x_te, y_te = _make(N_TEST, 2, protos)
    for name, arr in (
        ("train-images-idx3-ubyte", x_tr),
        ("train-labels-idx1-ubyte", y_tr),
        ("t10k-images-idx3-ubyte", x_te),
        ("t10k-labels-idx1-ubyte", y_te),
    ):
        path = os.path.join(OUT, name + ".gz")
        # fixed mtime/filename fields keep the gzip output byte-reproducible
        with open(path, "wb") as raw, gzip.GzipFile(
            fileobj=raw, mode="wb", filename="", mtime=0
        ) as f:
            f.write(_idx_bytes(arr))
        print(f"{path}: {os.path.getsize(path)} bytes")


if __name__ == "__main__":
    main()
