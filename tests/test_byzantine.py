"""Byzantine chaos (ISSUE 14): adversarial fault injection, robust async
merge, admission screening, and attacker quarantine on both control planes.

Five layers, mirroring the subsystem's structure:

- ByzantineSpec determinism + non-mutation at the fault seam (the same
  ``byz_corrupt_update`` both the live injector and the simulator run);
- the robust merge kernels against numpy references, and the buffer's
  arrival-order-independence contract under every kernel;
- the admission screen + suspicion EWMA + one-shot quarantine, and both
  aggregator seams consuming it (async ``offer``, sync ``add_model`` with
  delivering-peer attribution);
- malformed ``async_pull``/``async_view`` control payloads dropping
  loudly without killing the node (parity with ``async_update``);
- scale + acceptance: a simulated fleet with 10% sign-flip attackers
  fails with defenses off and converges (attackers quarantined,
  bit-exact replay) with them on; a live 6-node equivocation federation
  converges with the attacker evicted through the existing path; robust
  folds over sharded node-stacks keep the no-materialization contract.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from p2pfl_tpu.communication.faults import (
    ByzantineSpec,
    CrashSpec,
    EdgeFault,
    FaultInjector,
    FaultPlan,
    byz_corrupt_update,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.communication.message import WeightsEnvelope
from p2pfl_tpu.federation.buffer import BufferedAggregator
from p2pfl_tpu.federation.defense import ByzantineDefense
from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    yield
    Settings.FEDERATION_MODE = "sync"
    Settings.HIER_CLUSTER_SIZE = 0
    Settings.ASYNC_ROBUST_AGG = "fedavg"
    Settings.ASYNC_TRIM = 1
    Settings.BYZ_F = 1
    Settings.BYZ_SCREEN = False
    Settings.BYZ_SUSPICION_BETA = 0.5
    Settings.BYZ_SUSPICION_THRESHOLD = 0.7
    MemoryRegistry.reset()


def _sum_metric(metric):
    return sum(d.get(metric, 0.0) for d in logger.get_comm_metrics().values())


def _upd(value, origin, seq, base=0, dim=4, samples=1):
    u = ModelUpdate({"w": np.full(dim, value, np.float32)}, [origin], samples)
    u.version = (origin, seq, base)
    return u


# ---------------------------------------------------------------------------
# ByzantineSpec at the fault seam
# ---------------------------------------------------------------------------


def test_byz_corruption_deterministic_and_non_mutating():
    """Same (seed, plan) ⇒ bit-identical corruption; the honest update is
    never touched (in-process transports pass payloads by reference)."""
    for kind in ("sign_flip", "scale", "noise", "stale_replay", "equivocate"):
        plans = [
            FaultPlan(seed=11, byzantine={"a": ByzantineSpec(kind=kind, lam=3.0)})
            for _ in range(2)
        ]
        outs = []
        for plan in plans:
            honest = _upd(1.0, "a", 1)
            bad = byz_corrupt_update(plan, "a", "b", honest, "async_update")
            assert bad is not None, kind
            # the original is untouched and the corruption does not alias it
            np.testing.assert_array_equal(honest.params["w"], np.ones(4, np.float32))
            assert bad.params["w"] is not honest.params["w"]
            assert bad.version == honest.version  # the lie rides a true triple
            outs.append(np.asarray(bad.params["w"]))
        np.testing.assert_array_equal(outs[0], outs[1])
        if kind == "sign_flip":
            np.testing.assert_array_equal(outs[0], -np.ones(4, np.float32))
        if kind == "scale":
            np.testing.assert_array_equal(outs[0], 3.0 * np.ones(4, np.float32))


def test_byz_equivocation_differs_per_edge():
    plan = FaultPlan(seed=11, byzantine={"a": ByzantineSpec(kind="equivocate", lam=5.0)})
    to_b = byz_corrupt_update(plan, "a", "b", _upd(1.0, "a", 1), "async_update")
    to_c = byz_corrupt_update(plan, "a", "c", _upd(1.0, "a", 1), "async_update")
    assert not np.allclose(to_b.params["w"], to_c.params["w"])


def test_byz_stale_replay_freezes_first_payload():
    plan = FaultPlan(seed=11, byzantine={"a": ByzantineSpec(kind="stale_replay")})
    first = byz_corrupt_update(plan, "a", "b", _upd(1.0, "a", 1), "async_update")
    later = byz_corrupt_update(plan, "a", "b", _upd(9.0, "a", 7, base=5), "async_update")
    np.testing.assert_array_equal(later.params["w"], first.params["w"])
    assert later.version == ("a", 7, 5)  # fresh triple: vv dedup cannot catch it


def test_byz_scope_and_arming_do_not_shift_fault_verdicts():
    """Out-of-scope commands pass untouched, and arming an attack must not
    consume the drop/duplicate verdict streams (separate byz streams)."""
    spec = ByzantineSpec(kind="sign_flip")
    armed = FaultPlan(seed=3, default=EdgeFault(drop=0.3), byzantine={"a": spec})
    plain = FaultPlan(seed=3, default=EdgeFault(drop=0.3))
    assert byz_corrupt_update(armed, "a", "b", _upd(1.0, "a", 1), "async_model") is None
    assert byz_corrupt_update(armed, "x", "b", _upd(1.0, "x", 1), "async_update") is None
    byz_corrupt_update(armed, "a", "b", _upd(1.0, "a", 1), "async_update")
    draws_armed = [armed.rng("a", "b").random() for _ in range(16)]
    draws_plain = [plain.rng("a", "b").random() for _ in range(16)]
    assert draws_armed == draws_plain


def test_byz_corruption_not_disarmed_by_control_scoped_edge_fault():
    """A control-scoped edge fault and a Byzantine attacker are
    independent plan dimensions: the scope gate's weights short-circuit
    must not ship the attacker's payload uncorrupted (review-pinned)."""
    plan = FaultPlan(
        seed=3,
        default=EdgeFault(drop=1.0, scope="control"),
        byzantine={"a": ByzantineSpec(kind="sign_flip")},
    )
    sent = []

    def transport(nei, env, create_connection=False):
        sent.append(env)
        return True

    env = WeightsEnvelope("a", 0, "async_update", _upd(1.0, "a", 1))
    assert FaultInjector(plan, "a")("b", env, False, transport)
    assert len(sent) == 1  # weights pass the control-scoped drop...
    np.testing.assert_array_equal(  # ...but corrupted, not disarmed
        np.asarray(sent[0].update.params["w"]), -np.ones(4, np.float32)
    )


# ---------------------------------------------------------------------------
# robust merge kernels
# ---------------------------------------------------------------------------


def _stack(rows):
    return {"w": jnp.asarray(np.stack([np.asarray(r, np.float32) for r in rows]))}


def test_robust_kernels_against_numpy_reference():
    from p2pfl_tpu.ops.aggregation import buffered_robust_merge

    rows = [[1.0, 2.0], [1.2, 1.8], [0.8, 2.2], [100.0, -100.0]]  # last = poison
    stacked = _stack(rows)
    w = jnp.asarray([1.0, 2.0, 1.0, 1.0])
    arr = np.asarray(rows, np.float32)

    med = buffered_robust_merge(stacked, w, "median")
    np.testing.assert_allclose(np.asarray(med["w"]), np.median(arr, axis=0), rtol=1e-6)

    tm = buffered_robust_merge(stacked, w, "trimmed-mean", trim=1)
    ref = np.mean(np.sort(arr, axis=0)[1:-1], axis=0)
    np.testing.assert_allclose(np.asarray(tm["w"]), ref, rtol=1e-6)

    ks = buffered_robust_merge(stacked, w, "krum-screen", f=1)
    # Krum screens out the outlier; survivors fold at their weights
    sel = arr[:3]
    wsel = np.asarray([1.0, 2.0, 1.0], np.float32)
    ref = (wsel[:, None] * sel).sum(0) / wsel.sum()
    np.testing.assert_allclose(np.asarray(ks["w"]), ref, rtol=1e-5)

    fa = buffered_robust_merge(stacked, w, "fedavg")
    wf = np.asarray([1.0, 2.0, 1.0, 1.0], np.float32)
    ref = (wf[:, None] * arr).sum(0) / wf.sum()
    np.testing.assert_allclose(np.asarray(fa["w"]), ref, rtol=1e-5)

    with pytest.raises(ValueError, match="ASYNC_ROBUST_AGG"):
        buffered_robust_merge(stacked, w, "nonsense")


def test_robust_kernels_degrade_below_population():
    """Under-populated buffers fold the mean instead of refusing."""
    from p2pfl_tpu.ops.aggregation import buffered_robust_merge

    stacked = _stack([[2.0, 4.0]])
    w = jnp.ones(1)
    for kind in ("trimmed-mean", "median", "krum-screen", "fedavg"):
        out = np.asarray(buffered_robust_merge(stacked, w, kind)["w"])
        np.testing.assert_allclose(out, [2.0, 4.0], rtol=1e-6)


@pytest.mark.parametrize("kind", ["trimmed-mean", "median", "krum-screen"])
def test_buffer_flush_arrival_order_independent_per_kernel(kind):
    """The (origin, seq)-sorted determinism contract holds for every
    robust kernel, not just the weighted mean."""
    Settings.ASYNC_ROBUST_AGG = kind
    orders = [
        [("n1", 1.0), ("n2", 1.2), ("n3", 0.8), ("n4", 50.0)],
        [("n4", 50.0), ("n2", 1.2), ("n1", 1.0), ("n3", 0.8)],
    ]
    results = []
    for order in orders:
        buf = BufferedAggregator("agg", {"w": np.zeros(4, np.float32)}, k=4)
        res = None
        for origin, val in order:
            res = buf.offer(_upd(val, origin, 1)) or res
        results.append(np.asarray(res.params["w"]))
    np.testing.assert_array_equal(results[0], results[1])
    # and the poison stayed bounded: the merged value is near the honest ones
    assert float(np.abs(results[0]).max()) < 2.0


def test_buffer_robust_merge_keeps_version_and_regional_semantics():
    """Kernel swap changes the fold only: version minting (bump_on_flush)
    and the regional no-bump contract are untouched."""
    Settings.ASYNC_ROBUST_AGG = "median"
    gbuf = BufferedAggregator("g", {"w": np.zeros(4, np.float32)}, k=2)
    rbuf = BufferedAggregator("r", {"w": np.zeros(4, np.float32)}, k=2, bump_on_flush=False)
    for i, buf in enumerate((gbuf, rbuf)):
        a = buf.offer(_upd(1.0, f"a{i}", 1))
        b = buf.offer(_upd(3.0, f"b{i}", 1))
        assert a is None and b is not None
    assert gbuf.version == 1 and rbuf.version == 0


# ---------------------------------------------------------------------------
# screening + suspicion + quarantine
# ---------------------------------------------------------------------------


def test_screen_stats_math():
    from p2pfl_tpu.ops.aggregation import screen_stats

    rng = np.random.default_rng(5)
    p = {"a": rng.normal(size=(8,)).astype(np.float32), "b": rng.normal(size=(3,)).astype(np.float32)}
    r = {"a": rng.normal(size=(8,)).astype(np.float32), "b": rng.normal(size=(3,)).astype(np.float32)}
    pn, rn, cos = screen_stats(p, r)
    pf = np.concatenate([p["a"], p["b"]])
    rf = np.concatenate([r["a"], r["b"]])
    np.testing.assert_allclose(float(pn), np.linalg.norm(pf), rtol=1e-5)
    np.testing.assert_allclose(float(rn), np.linalg.norm(rf), rtol=1e-5)
    np.testing.assert_allclose(
        float(cos), pf @ rf / (np.linalg.norm(pf) * np.linalg.norm(rf)), rtol=1e-4, atol=1e-6
    )


def test_defense_gates_ewma_and_one_shot_quarantine():
    Settings.BYZ_SCREEN = True
    fired = []
    d = ByzantineDefense("agg", on_quarantine=fired.append)
    ref = {"w": np.ones(8, np.float32)}
    # honest: near the global
    assert d.admit("x", {"w": np.full(8, 1.01, np.float32)}, ref)
    assert d.suspicion("x") == 0.0
    # sign flip: cos gate
    assert not d.admit("x", {"w": -np.ones(8, np.float32)}, ref)
    # scale: norm gate
    assert not d.admit("x", {"w": np.full(8, 100.0, np.float32)}, ref)
    deadline = time.monotonic() + 5
    while not fired and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fired == ["x"] and d.is_quarantined("x")
    assert d.take_quarantined() == ["x"] and d.take_quarantined() == []
    # quarantine is monotone: even honest payloads are dropped now, and
    # the callback never fires twice
    assert not d.admit("x", {"w": np.ones(8, np.float32)}, ref)
    time.sleep(0.05)
    assert fired == ["x"]
    # self-contributions and zero-norm references abstain
    assert d.admit("agg", {"w": -np.ones(8, np.float32)}, ref)
    assert d.admit("y", {"w": -np.ones(8, np.float32)}, {"w": np.zeros(8, np.float32)})


def test_defense_screen_off_only_enforces_quarantine():
    Settings.BYZ_SCREEN = False
    d = ByzantineDefense("agg")
    ref = {"w": np.ones(4, np.float32)}
    assert d.admit("x", {"w": -np.ones(4, np.float32)}, ref)  # no screening
    assert d.suspicion("x") == 0.0


def test_buffer_offer_screens_and_quarantines():
    Settings.BYZ_SCREEN = True
    logger.reset_comm_metrics()
    d = ByzantineDefense("agg")
    buf = BufferedAggregator("agg", {"w": np.ones(4, np.float32)}, k=3, defense=d)
    assert buf.offer(_upd(-1.0, "evil", 1)) is None
    assert buf.offer(_upd(-1.0, "evil", 2)) is None
    assert d.is_quarantined("evil")
    # post-quarantine, even an honest-looking payload from it is dropped
    assert buf.offer(_upd(1.0, "evil", 3)) is None
    assert buf.pending() == 0
    # honest contributors still merge
    for i, (origin, val) in enumerate([("a", 1.0), ("b", 1.1), ("c", 0.9)]):
        res = buf.offer(_upd(val, origin, 1))
    assert res is not None and res.version == 1
    assert _sum_metric("screen_reject") >= 2
    assert _sum_metric("byz_suspect") >= 2
    assert _sum_metric("byz_evicted") >= 1
    assert _sum_metric("byz_quarantined_drop") >= 1


def test_async_screen_attributes_to_deliverer_not_payload_origin():
    """The version triple's origin is ATTACKER-CONTROLLED: poison stamped
    with an honest node's origin must indict the delivering peer, or a
    lying sender could frame (and evict) the honest node (review-pinned)."""
    Settings.BYZ_SCREEN = True
    d = ByzantineDefense("agg")
    buf = BufferedAggregator("agg", {"w": np.ones(4, np.float32)}, k=3, defense=d)
    poison = _upd(-1.0, "victim", 1)  # framed: origin says "victim"
    assert buf.offer(poison, screen_origin="attacker") is None
    assert d.suspicion("attacker") > 0.0
    assert d.suspicion("victim") == 0.0
    # and the victim's real contributions keep merging after the
    # attacker crosses the threshold
    assert buf.offer(_upd(-1.0, "victim", 2), screen_origin="attacker") is None
    assert d.is_quarantined("attacker") and not d.is_quarantined("victim")
    assert buf.offer(_upd(1.0, "victim", 3), screen_origin="victim") is None  # buffers
    assert buf.pending() == 1


def test_add_model_screens_with_source_attribution():
    """The sync seam: a poisoned payload indicts the DELIVERING peer (a
    corrupted relay must not frame the honest contributor named inside)."""
    from p2pfl_tpu.learning.aggregators.fedavg import FedAvg

    Settings.BYZ_SCREEN = True
    d = ByzantineDefense("me")
    agg = FedAvg("me")
    agg.defense = d
    ref = {"w": np.ones(4, np.float32)}
    agg.set_screen_reference(ref)
    agg.set_nodes_to_aggregate(["me", "honest", "attacker"])
    # the attacker relays a corrupted copy of honest's model
    poisoned = ModelUpdate({"w": -np.ones(4, np.float32)}, ["honest"], 1)
    assert agg.add_model(poisoned, source="attacker") == []
    assert d.suspicion("attacker") > 0.0 and d.suspicion("honest") == 0.0
    # honest's real model, delivered by honest, is accepted
    good = ModelUpdate({"w": np.full(4, 1.05, np.float32)}, ["honest"], 1)
    assert agg.add_model(good, source="honest") == ["honest"]


def test_add_model_rejects_partial_acc_for_robust_aggregators():
    """SUPPORTS_PARTIALS=False strategies fail LOUDLY on a fused-round
    accumulator instead of silently folding pre-averaged state."""
    from p2pfl_tpu.learning.aggregators.krum import Krum
    from p2pfl_tpu.learning.aggregators.trimmed_mean import TrimmedMean

    for cls in (Krum, TrimmedMean):
        agg = cls("me")
        agg.set_nodes_to_aggregate(["me", "peer"])
        fused = ModelUpdate({"w": np.ones(4, np.float32)}, ["me"], 1)
        fused.partial_acc = ({"w": np.ones(4, np.float32)}, np.float32(1.0))
        with pytest.raises(ValueError, match="SUPPORTS_PARTIALS"):
            agg.add_model(fused)
        agg.clear()
    # FedAvg (partial-supporting) keeps accepting the accumulator seam
    from p2pfl_tpu.learning.aggregators.fedavg import FedAvg

    agg = FedAvg("me")
    agg.set_nodes_to_aggregate(["me", "peer"])
    fused = ModelUpdate({"w": np.ones(4, np.float32)}, ["me"], 1)
    fused.partial_acc = (
        {"w": jnp.ones(4, dtype=jnp.float32)},
        jnp.float32(1.0),
    )
    assert agg.add_model(fused) == ["me"]


# ---------------------------------------------------------------------------
# malformed control payloads (async_pull / async_view fuzz)
# ---------------------------------------------------------------------------


def test_malformed_async_ctl_payloads_drop_loudly_without_killing_node():
    """Parity with async_update's decode-or-drop: garbage async_pull /
    async_view frames are counted + dropped, the node keeps serving, and
    a later experiment on the same overlay works."""
    Settings.FEDERATION_MODE = "async"
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(2)]
    for n in nodes:
        n.start()
    try:
        full_connection(nodes[0], nodes)
        wait_convergence(nodes, 1, only_direct=True, wait=10)
        victim = nodes[0]
        garbage = ModelUpdate(None, [nodes[1].addr], 1, encoded=b"NOT WEIGHTS")
        # a weights frame hijacking the control verbs
        for cmd in ("async_pull", "async_view"):
            res = victim.protocol._dispatch(cmd, nodes[1].addr, 0, [], garbage)
            assert res.ok  # absorbed, not an escaping error
        # async_view with missing/garbage member lists
        for args in ([], ["only-one"], ["\x00\xff;;;", ""]):
            res = victim.protocol._dispatch("async_view", nodes[1].addr, 0, list(args), None)
            assert res.ok
        # the weights-frame variants count even with no context installed;
        # view-arg validation needs one — install a live experiment and
        # re-fuzz mid-run
        assert _sum_metric("async_ctl_malformed") >= 2
        nodes[0].set_start_learning(rounds=2, epochs=1)
        deadline = time.monotonic() + 10
        while victim.async_ctx is None and time.monotonic() < deadline:
            time.sleep(0.02)
        for args in ([], ["only-one"]):
            res = victim.protocol._dispatch("async_view", nodes[1].addr, 0, list(args), None)
            assert res.ok
        res = victim.protocol._dispatch("async_pull", nodes[1].addr, 0, [], garbage)
        assert res.ok
        assert _sum_metric("async_ctl_malformed") >= 5
        wait_to_finish(nodes, timeout=30)
        assert all(n._running for n in nodes)
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in nodes]
        np.testing.assert_allclose(params[0], params[1], atol=1e-6)
    finally:
        for n in nodes:
            n.stop()


# ---------------------------------------------------------------------------
# simulated scale: defense off vs on, replay
# ---------------------------------------------------------------------------


def _byz_fleet(n, frac, kind, seed=1905, updates=6, cluster=16, target=0.5):
    attackers = {
        f"sim-{i:04d}": ByzantineSpec(kind=kind)
        for i in range(0, n, max(1, int(round(1 / frac))))
    }
    plan = FaultPlan(seed=seed, byzantine=attackers)
    fleet = SimulatedAsyncFleet(
        n, seed=seed, cluster_size=cluster, k=4,
        updates_per_node=updates, target_loss=target,
    )
    fleet.plan = plan
    return fleet, attackers


def test_simfleet_byzantine_defense_off_fails_on_converges_and_replays():
    """The acceptance drive at test scale (the 1k row lives in
    BENCH_ASYNC): 10% sign-flip attackers — undefended, the fleet never
    reaches the loss target; with ASYNC_ROBUST_AGG + screening on it
    converges, quarantines attackers through the eviction machinery, and
    the whole run replays bit-exact from (seed, plan)."""
    n, frac = 200, 0.10

    Settings.BYZ_SCREEN = False
    Settings.ASYNC_ROBUST_AGG = "fedavg"
    undefended = _byz_fleet(n, frac, "sign_flip")[0].run()
    assert undefended.byz_corrupted > 0
    assert undefended.time_to_target is None  # measurably fails

    Settings.BYZ_SCREEN = True
    Settings.ASYNC_ROBUST_AGG = "trimmed-mean"
    runs = [_byz_fleet(n, frac, "sign_flip")[0].run() for _ in range(2)]
    defended, replay = runs
    attackers = _byz_fleet(n, frac, "sign_flip")[1]
    assert defended.time_to_target is not None
    assert defended.final_loss() < undefended.final_loss() / 10
    assert defended.screen_rejects > 0
    # quarantined attackers really are attackers (no honest node evicted)
    assert set(defended.quarantined) <= set(attackers)
    assert len(defended.quarantined) >= len(attackers) // 2
    # bit-exact replay: loss curve, quarantine sequence, corruption count
    assert replay.loss_curve == defended.loss_curve
    assert replay.quarantined == defended.quarantined
    assert replay.byz_corrupted == defended.byz_corrupted
    np.testing.assert_array_equal(replay.params["w"], defended.params["w"])


def test_simfleet_byzantine_composes_with_crash_chaos():
    """Adversaries are one more fault class: a plan mixing sign-flip
    attackers with crashes still replays bit-exact and still converges
    with defenses on."""
    Settings.BYZ_SCREEN = True
    Settings.ASYNC_ROBUST_AGG = "median"

    def drive():
        plan = FaultPlan(
            seed=7,
            default=EdgeFault(drop=0.02),
            byzantine={"sim-0005": ByzantineSpec(kind="scale", lam=40.0)},
            crashes={"sim-0011": CrashSpec(stage="AsyncTrainStage", round_no=1)},
        )
        fleet = SimulatedAsyncFleet(
            24, seed=7, cluster_size=8, k=3, updates_per_node=5, target_loss=0.5
        )
        fleet.plan = plan
        return fleet.run()

    a, b = drive(), drive()
    assert a.loss_curve == b.loss_curve and a.quarantined == b.quarantined
    assert a.crashed == ["sim-0011"]
    assert a.quarantined == ["sim-0005"]
    # bounded damage: a λ=40 scale attack through an undefended mean would
    # blow the consensus loss past 1e2; the median keeps it at the rank
    # kernel's small-fleet bias (median-of-targets vs weighted-mean target)
    assert a.final_loss() < 5.0


# ---------------------------------------------------------------------------
# live fleet: equivocation attacker quarantined via the eviction path
# ---------------------------------------------------------------------------


def test_async_live_equivocation_federation_quarantines_attacker():
    """ISSUE 14 acceptance (threaded half): 6 nodes in 2 clusters, one
    EQUIVOCATING attacker (a different corrupted payload per edge per
    send). With robust merge + screening on, the survivors converge and
    the attacker is evicted by the same machinery that evicts a corpse
    (defense → Neighbors.evict → mark_dead → TierRouter re-derivation)."""
    Settings.FEDERATION_MODE = "async"
    Settings.FEDBUFF_K = 3
    Settings.HIER_CLUSTER_SIZE = 3
    Settings.ASYNC_ROBUST_AGG = "trimmed-mean"
    Settings.BYZ_SCREEN = True
    Settings.BYZ_SUSPICION_BETA = 0.8  # one clear rejection quarantines
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(6)]
    for n in nodes:
        n.start()
    try:
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 5, only_direct=True, wait=10)
        # members sort node-1..node-6 → clusters [1,2,3],[4,5,6]; pick an
        # EDGE (not a regional, not the root) as the attacker
        by_addr = {n.addr: n for n in nodes}
        attacker = by_addr[sorted(by_addr)[1]]
        plan = FaultPlan(
            seed=1905,
            byzantine={attacker.addr: ByzantineSpec(kind="equivocate", lam=40.0)},
        )
        install_fault_plan(nodes, plan)
        survivors = [n for n in nodes if n is not attacker]
        nodes[0].set_start_learning(rounds=3, epochs=1)
        wait_to_finish(nodes, timeout=45)
        assert _sum_metric("fault_byzantine") >= 1
        assert _sum_metric("screen_reject") >= 1
        assert _sum_metric("byz_evicted") >= 1  # quarantine fired
        # the existing eviction path ran: somebody marked the attacker
        # dead and re-derived (membership_changed counts every event)
        assert _sum_metric("membership_changed") >= 1
        # survivors converged on one finite global
        params = [np.asarray(n.learner.get_parameters()["w"]) for n in survivors]
        assert np.all(np.isfinite(params[0]))
        for p in params[1:]:
            np.testing.assert_allclose(p, params[0], atol=1e-5)
        # bounded damage: one equivocated payload inside both gates can
        # leak before quarantine lands (the documented threat model — the
        # norm gate caps it at gate x the global's norm), but a λ=40
        # payload landing at full weight would sit two orders higher; the
        # QUANTITATIVE convergence claim is the simulated drive's
        assert float(np.abs(params[0]).max()) < 50.0
    finally:
        remove_fault_plan(nodes)
        for n in nodes:
            n.stop()


# ---------------------------------------------------------------------------
# robust folds over sharded node-stacks (PR-10 contract)
# ---------------------------------------------------------------------------


def test_robust_fold_stacked_sharded_median_matches_numpy():
    """Per-coordinate median over a node-axis-SHARDED stack: result
    matches numpy, output lands under the requested (model-sharded)
    specs — the fold never needs a full-model gather."""
    from p2pfl_tpu.ops.aggregation import robust_fold_stacked
    from p2pfl_tpu.parallel.mesh import federation_mesh

    rng = np.random.default_rng(3)
    n = 4
    mesh = federation_mesh(devices=jax.devices()[:n])
    shard = NamedSharding(mesh, P(Settings.MESH_NODES_AXIS))
    stacked = {
        "a": jax.device_put(rng.normal(size=(n, 6, 4)).astype(np.float32), shard),
        "b": jax.device_put(rng.normal(size=(n, 8)).astype(np.float32), shard),
    }
    ref = jax.tree.map(lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), stacked)
    out_sh = {
        "a": NamedSharding(mesh, P(None, Settings.MESH_MODEL_AXIS)),
        "b": NamedSharding(mesh, P()),
    }
    for kind in ("median", "trimmed-mean"):
        fold = jax.jit(
            lambda s, kind=kind: robust_fold_stacked(s, ref, kind, trim=1),
            out_shardings=out_sh,
        )
        out = fold(stacked)
        want = (
            np.median(np.asarray(stacked["a"]), axis=0)
            if kind == "median"
            else np.mean(np.sort(np.asarray(stacked["a"]), axis=0)[1:-1], axis=0)
        )
        np.testing.assert_allclose(np.asarray(out["a"]), want, rtol=1e-5, atol=1e-6)
        assert out["a"].sharding.spec == P(None, Settings.MESH_MODEL_AXIS)


def _mk_sharded(robust_agg, n=4, model_parallel=2, vote=False):
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import ShardedNodeFederation

    rules = (
        (r"Dense_0/kernel", (None, "model")),
        (r"Dense_1/kernel", ("model", None)),
        (r"Dense_2/kernel", (None, "model")),
        (r".*", ()),
    )
    data = FederatedDataset.synthetic_mnist(n_train=64 * n, n_test=32, seed=5)
    return ShardedNodeFederation.from_dataset(
        mlp(seed=0), data, n_nodes=n, rules=rules, model_parallel=model_parallel,
        batch_size=16, vote=vote, seed=3, optimizer="sgd", learning_rate=1e-2,
        robust_agg=robust_agg,
    )


def test_sharded_federation_robust_fold_survives_poison_without_materializing():
    """A sharded node whose slice diverges wildly (a Byzantine slice) is
    absorbed by the median fold — and the robust fold keeps the PR-10
    contract: inputs node-sharded, outputs model-sharded, no device holds
    a full-model stack entry it shouldn't."""
    from p2pfl_tpu.parallel.submesh import per_device_bytes, slice_views

    fed = _mk_sharded("median")
    fed.run_round(epochs=1)
    honest = [np.concatenate([np.asarray(x).ravel() for x in jax.tree.leaves(fed.node_params(0))])]
    # poison node 3's params in place (a Byzantine slice between rounds)
    poisoned = jax.tree.map(lambda x: x * -37.0, fed.params[3])
    fed.params[3] = poisoned
    fed.run_round(epochs=1)
    # fold input shardings: node-stacked params sharded over nodes
    for sharding in jax.tree.leaves(
        fed.last_fold["psum_shardings"], is_leaf=lambda x: hasattr(x, "spec")
    ):
        assert sharding.spec[0] == Settings.MESH_NODES_AXIS
        assert not sharding.is_fully_replicated
    # the aggregate stayed sane (the poisoned slice was rank-rejected):
    after = np.concatenate(
        [np.asarray(x).ravel() for x in jax.tree.leaves(fed.node_params(0))]
    )
    assert np.all(np.isfinite(after))
    assert float(np.abs(after).max()) < 50.0  # -37x poison would dominate a mean
    # live-buffer bound: no device holds a full params copy post-round
    full = sum(np.asarray(x).nbytes for x in jax.tree.leaves(fed.model.params))
    per_dev = per_device_bytes(fed.params)
    assert max(per_dev.values()) < full  # model_parallel=2 ⇒ ~half + slack


def test_sharded_robust_fold_requires_full_participation():
    fed = _mk_sharded("trimmed-mean")
    fed.drop_node(2)
    with pytest.raises(RuntimeError, match="full participation"):
        fed.run_round(epochs=1)
