"""FedOpt server optimizers, FedProx proximal local steps, SCAFFOLD control
variates. The reference ships FedAvg only (`p2pfl/learning/aggregators/`)
and lists Scaffold as "coming soon" (`docs/source/library_design.md`) —
this family covers heterogeneous-shard convergence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.learning.aggregators import FedAdagrad, FedAdam, FedYogi
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import SpmdFederation


def _updates(values, n=3):
    return [
        ModelUpdate({"w": jnp.full((4,), v)}, [f"n{i}"], 10)
        for i, v in enumerate(values[:n])
    ]


@pytest.mark.parametrize("cls", [FedAdam, FedYogi, FedAdagrad])
def test_fedopt_steps_toward_average(cls):
    agg = cls("test", server_lr=0.1)
    r0 = agg.aggregate(_updates([1.0, 1.0, 1.0]))
    np.testing.assert_allclose(np.asarray(r0.params["w"]), 1.0)  # round 0 adopts avg

    # clients moved to 0.0: pseudo-grad = prev - avg = 1.0, server steps DOWN
    r1 = agg.aggregate(_updates([0.0, 0.0, 0.0]))
    w1 = float(r1.params["w"][0])
    assert w1 < 1.0
    # repeated identical signal keeps moving toward the average
    r2 = agg.aggregate(_updates([0.0, 0.0, 0.0]))
    assert float(r2.params["w"][0]) < w1
    assert bool(jnp.isfinite(r2.params["w"]).all())


def test_fedopt_contributors_and_state_survive_clear():
    agg = FedAdam("test")
    agg.aggregate(_updates([1.0, 1.0]))
    agg.clear()  # round bookkeeping reset must NOT wipe server moments
    r = agg.aggregate(_updates([0.0, 0.0]))
    assert r.contributors == ["n0", "n1"]
    assert agg._t == 1  # server stepped, state survived


def test_fedopt_experiment_reset_drops_server_state():
    """ADVICE r2: a SECOND experiment on the same node must not server-step
    its round 0 against the previous experiment's final global — the
    experiment-boundary hook wipes moments and the previous-global anchor
    (per-round clear() deliberately keeps them)."""
    agg = FedAdam("test")
    agg.aggregate(_updates([1.0, 1.0]))
    agg.aggregate(_updates([0.0, 0.0]))
    assert agg._t == 1 and agg._prev is not None
    agg.reset_experiment()
    assert agg._t == 0 and agg._prev is None and agg._m is None and agg._v is None
    # fresh experiment bootstraps like round 0 again (adopts the average)
    r = agg.aggregate(_updates([3.0, 5.0]))
    assert agg._t == 0
    np.testing.assert_allclose(np.asarray(r.params["w"]).mean(), 4.0)


@pytest.mark.slow
def test_fedopt_node_federation_converges():
    """2-node federation with FedAdam aggregation through the full stack."""
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils import check_equal_models, wait_to_finish

    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    nodes = []
    for i in range(2):
        learner = JaxLearner(mlp(), data.partition(i, 2), epochs=1, batch_size=32)
        # tau tempers the adaptive step on tiny-scale weights (Reddi et al.
        # tune τ per task; 1e-3 overshoots this toy MLP)
        n = Node(learner=learner, aggregator=FedAdam(server_lr=0.01, tau=1e-2))
        n.start()
        nodes.append(n)
    try:
        nodes[1].connect(nodes[0].addr)
        nodes[0].set_start_learning(rounds=3, epochs=1)
        wait_to_finish(nodes, timeout=120)
        check_equal_models(nodes)
        assert nodes[0].learner.evaluate()["test_acc"] > 0.5
        # back-to-back SECOND experiment on the same nodes (ADVICE r2): the
        # stage wiring must reset server state — _t counts this experiment's
        # server steps only (3 rounds → ≤2 steps; stale state would carry
        # the first experiment's count past that)
        ts_after_first = max(n.aggregator._t for n in nodes)
        assert 1 <= ts_after_first <= 2
        nodes[0].set_start_learning(rounds=3, epochs=1)
        wait_to_finish(nodes, timeout=120)
        check_equal_models(nodes)
        assert max(n.aggregator._t for n in nodes) <= 2
    finally:
        for n in nodes:
            n.stop()


@pytest.mark.slow
def test_fedopt_gossips_individual_models():
    """FedOpt is stateful+nonlinear: it must NOT pre-aggregate gossip
    partials (that would advance server moments mid-round and emit
    server-stepped payloads peers re-average). 3-node federation converges
    with equal models — the path where partial gossip would corrupt."""
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings
    from p2pfl_tpu.utils import check_equal_models, wait_to_finish

    assert FedAdam.SUPPORTS_PARTIALS is False
    assert FedAdam.ALWAYS_AGGREGATE is True

    old = Settings.TRAIN_SET_SIZE
    Settings.TRAIN_SET_SIZE = 3
    # timing-sensitive e2e: under a saturated host (suite running next to
    # benches) the shrunken test timeouts can cut a round short — widen them
    old_agg, old_gossip = Settings.AGGREGATION_TIMEOUT, Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS
    Settings.AGGREGATION_TIMEOUT = max(Settings.AGGREGATION_TIMEOUT, 60.0)
    Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = max(old_gossip, 12)
    data = FederatedDataset.synthetic_mnist(n_train=384, n_test=64)
    nodes = []
    try:
        for i in range(3):
            learner = JaxLearner(mlp(), data.partition(i, 3), epochs=1, batch_size=32)
            n = Node(learner=learner, aggregator=FedAdam(server_lr=0.01, tau=1e-2))
            n.start()
            nodes.append(n)
        nodes[1].connect(nodes[0].addr)
        nodes[2].connect(nodes[0].addr)
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(nodes, timeout=120)
        check_equal_models(nodes)
        # at least one node computed the round-2 aggregate (server step);
        # a node that received a faster peer's finished aggregate resyncs
        # via on_result without stepping (_t stays lower) — both end equal
        ts = [n.aggregator._t for n in nodes]
        assert max(ts) >= 1 and all(t <= 1 for t in ts)
    finally:
        Settings.TRAIN_SET_SIZE = old
        Settings.AGGREGATION_TIMEOUT = old_agg
        Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = old_gossip
        for n in nodes:
            n.stop()


@pytest.mark.slow
def test_scaffold_fedopt_checkpoint_roundtrip(tmp_path):
    """save/restore must carry SCAFFOLD variates and FedOpt server moments —
    silently zeroing them on resume degrades the algorithm."""
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=64)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False,
        scaffold=True, optimizer="sgd", learning_rate=0.05,
        server_opt="adam", server_lr=0.01,
    )
    fed.run(rounds=2, epochs=1)
    fed.save(str(tmp_path / "fed"))

    fed2 = SpmdFederation.from_dataset(
        mlp(seed=7), data, n_nodes=4, batch_size=64, vote=False,
        scaffold=True, optimizer="sgd", learning_rate=0.05,
        server_opt="adam", server_lr=0.01,
    )
    fed2.restore(str(tmp_path / "fed"))
    assert fed2.round == 2 and fed2._server_t == 2
    for a, b in zip(jax.tree.leaves(fed.c_global), jax.tree.leaves(fed2.c_global)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(fed.opt_m), jax.tree.leaves(fed2.opt_m)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_fedprox_pulls_toward_anchor():
    """Large μ keeps the trained params measurably closer to the start."""
    from p2pfl_tpu.learning.learner import JaxLearner

    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=64)

    def drift(mu):
        learner = JaxLearner(mlp(), data, epochs=2, batch_size=64, prox_mu=mu)
        start = jax.tree.map(jnp.copy, learner.params)
        learner.fit()
        return sum(
            float(jnp.sum((a - b) ** 2))
            for a, b in zip(jax.tree.leaves(learner.params), jax.tree.leaves(start))
        )

    assert drift(mu=10.0) < drift(mu=0.0) * 0.8


@pytest.mark.slow
def test_spmd_fedprox_learns():
    data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False, prox_mu=0.1
    )
    before = fed.evaluate()["test_acc"]
    fed.run(rounds=2, epochs=1)
    assert fed.evaluate()["test_acc"] > before


def test_spmd_scaffold_learns_and_updates_variates():
    data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False,
        scaffold=True, optimizer="sgd", learning_rate=0.05,
    )
    before = fed.evaluate()["test_acc"]
    fed.run(rounds=3, epochs=1)
    assert fed.evaluate()["test_acc"] > before
    # the server control variate moved off its zero init
    assert max(float(jnp.abs(x).max()) for x in jax.tree.leaves(fed.c_global)) > 0


@pytest.mark.slow
def test_spmd_scaffold_partial_train_set():
    """Variates only update for elected nodes; the round still runs."""
    from p2pfl_tpu.settings import Settings

    old = Settings.TRAIN_SET_SIZE
    Settings.TRAIN_SET_SIZE = 2
    try:
        data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=4, batch_size=64, vote=True,
            scaffold=True, optimizer="sgd", learning_rate=0.05,
        )
        fed.run_round(epochs=1)
        assert int(fed.train_mask.sum()) == 2
        # non-elected nodes' local variates stayed exactly zero
        leaves = jax.tree.leaves(fed.c_local)
        out_idx = np.flatnonzero(fed.train_mask == 0)
        for x in leaves:
            assert float(jnp.abs(jnp.asarray(x)[out_idx]).max()) == 0.0
    finally:
        Settings.TRAIN_SET_SIZE = old


@pytest.mark.slow
def test_spmd_server_opt_learns():
    """SPMD FedOpt: server Adam on the pseudo-gradient, moments carried."""
    data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=4, batch_size=64, vote=False,
        server_opt="adam", server_lr=0.01,
    )
    before = fed.evaluate()["test_acc"]
    fed.run(rounds=3, epochs=1)
    assert fed.evaluate()["test_acc"] > before
    assert fed._server_t == 3
    assert max(float(jnp.abs(x).max()) for x in jax.tree.leaves(fed.opt_m)) > 0


def test_spmd_server_opt_rejects_unknown():
    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    with pytest.raises(ValueError, match="server_opt"):
        SpmdFederation.from_dataset(
            mlp(), data, n_nodes=2, batch_size=64, server_opt="rmsprop"
        )


def test_spmd_scaffold_requires_sgd():
    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    with pytest.raises(ValueError, match="sgd"):
        SpmdFederation.from_dataset(
            mlp(), data, n_nodes=2, batch_size=64, scaffold=True
        )


@pytest.mark.slow
def test_scaffold_beats_matched_fedavg_on_noniid():
    """SCAFFOLD's drift correction must beat FedAvg under the SAME local
    SGD on Dirichlet(0.3) non-IID shards (Karimireddy et al. 2020). Round
    4's bench compared it against FedAvg-with-ADAM and mis-read the result
    as a SCAFFOLD defect; this pins the matched-optimizer ordering at the
    regime where the correction matters (lr 0.02, 1 local epoch, seeds
    averaged — measured margin ~0.25 mean acc, far above seed noise)."""
    import numpy as np

    from p2pfl_tpu.learning.dataset import FederatedDataset as FD

    data = FD.mnist(None, modes=8, noise=0.7, proto_scale=0.5)

    def final_acc(seed, **kwargs):
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=8, strategy="dirichlet", alpha=0.3,
            batch_size=64, vote=False, seed=seed,
            optimizer="sgd", learning_rate=0.02, **kwargs,
        )
        entries = fed.run_fused(10, epochs=1, eval=True)
        return float(entries[-1]["test_acc"])

    fa = np.mean([final_acc(s) for s in (7, 11)])
    sc = np.mean([final_acc(s, scaffold=True) for s in (7, 11)])
    assert sc > fa + 0.05, (sc, fa)
