"""Fault-tolerance semantics in SPMD mode + per-round voting option."""

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import SpmdFederation
from p2pfl_tpu.settings import Settings


def _fed(n=4, **kw):
    data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    kw.setdefault("vote", False)
    return SpmdFederation.from_dataset(mlp(), data, n_nodes=n, batch_size=64, **kw)


@pytest.mark.slow
def test_drop_node_mid_training():
    """A dropped node stops contributing; the federation keeps converging."""
    fed = _fed()
    fed.run_round()
    fed.drop_node(3)
    fed.run_round()
    assert fed.evaluate()["test_acc"] > 0.9
    # restore and continue
    fed.restore_node(3)
    fed.run_round()
    assert fed.round == 3


def test_all_nodes_down_raises():
    fed = _fed(n=2)
    fed.drop_node(0)
    fed.drop_node(1)
    with pytest.raises(RuntimeError, match="no active"):
        fed.run_round()


def test_dropped_node_does_not_poison_aggregate():
    """Poison a node, then drop it: the aggregate must stay clean."""
    import jax

    fed = _fed()
    poisoned = jax.tree.map(
        lambda x: x.at[2].set(jax.random.normal(jax.random.PRNGKey(1), x.shape[1:]) * 1e3),
        fed.params,
    )
    fed.params = poisoned
    fed.drop_node(2)
    fed.run_round()
    assert fed.evaluate()["test_acc"] > 0.9  # plain fedavg, poison masked out


def test_vote_every_round():
    Settings.TRAIN_SET_SIZE = 2
    Settings.VOTE_EVERY_ROUND = True
    try:
        fed = _fed(vote=True)
        fed.run_round()
        m1 = fed.train_mask.copy()
        # across several rounds the elected pair should change at least once
        changed = False
        for _ in range(6):
            fed.run_round()
            if not np.array_equal(fed.train_mask, m1):
                changed = True
                break
        assert changed
    finally:
        Settings.VOTE_EVERY_ROUND = False


def test_init_multihost_noop_single_host():
    from p2pfl_tpu.parallel.distributed import init_multihost

    info = init_multihost()
    assert info["process_count"] >= 1
    assert info["local_devices"] >= 1
