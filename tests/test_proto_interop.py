"""Protobuf wire interop (``communication/proto_wire.py``).

The reference speaks generated-protobuf gRPC on
``/node.NodeServices/{handshake,disconnect,send_message,send_weights}``
(its proto declares ``package node;``); these tests pin (a) frame
round-trips through the reference-schema messages, (b) format sniffing —
mixed envelope/protobuf federations interoperate with no receiver
configuration, (c) the documented security divergence: foreign (non-P2TW)
weight payloads are rejected, never unpickled, and (d) REAL interop: a
repo server driven by the reference's own generated stubs on the
reference's method paths, and a repo client dialing a reference-stub
server — both directions, no self-referential codec loops.
"""

import importlib
import sys
import time

import numpy as np
import pytest

from p2pfl_tpu.communication import proto_wire as pw
from p2pfl_tpu.communication.grpc_transport import (
    GrpcProtocol,
    encode_message,
    encode_weights,
)
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import check_equal_models, wait_convergence, wait_to_finish

pytestmark = pytest.mark.skipif(not pw.HAVE_PROTOBUF, reason="protobuf runtime absent")


@pytest.fixture(autouse=True)
def _restore_format():
    yield
    Settings.WIRE_FORMAT = "envelope"


def test_message_roundtrip_and_sniffing():
    msg = Message("1.2.3.4:5", "vote_train_set", ("a", "1"), round=3, ttl=7)
    data = pw.encode_message_pb(msg)
    assert pw.is_protobuf_message(data)
    assert not pw.is_protobuf_message(encode_message(msg))  # JSON starts '{'
    back = pw.decode_message_pb(data)
    assert (back.source, back.cmd, back.args, back.round, back.ttl) == (
        msg.source, msg.cmd, msg.args, msg.round, msg.ttl
    )
    # the reference's int64 hash carries dedup identity: stable across hops
    assert back.msg_id == pw.decode_message_pb(data).msg_id
    # unset optional round maps to our -1 sentinel
    no_round = pw.decode_message_pb(pw.encode_message_pb(Message("s", "beat")))
    assert no_round.round == -1


def test_relay_keeps_dedup_hash_stable():
    """A relayed protobuf message must carry the SAME int64 hash on every
    hop — re-hashing per hop would defeat gossip dedup entirely (each
    receiver would dispatch the same command once per hop). Reference
    nodes use Python's SIGNED hash, so negative values round-trip too."""
    msg = Message("n1:1", "vote_train_set", ("a", "1"), round=0, ttl=5)
    hop1 = pw.decode_message_pb(pw.encode_message_pb(msg))
    hop2 = pw.decode_message_pb(pw.encode_message_pb(hop1))  # the relay
    assert hop1.msg_id == hop2.msg_id

    for h in (-1234, -(1 << 63), (1 << 63) - 1):  # incl. the int64 extremes
        neg = pw.pb.Message(source="ref:1", ttl=5, hash=h, cmd="beat").SerializeToString()
        ref_hop1 = pw.decode_message_pb(neg)
        ref_hop2 = pw.decode_message_pb(pw.encode_message_pb(ref_hop1))
        assert ref_hop1.msg_id == ref_hop2.msg_id == str(h)

    # a peer-controlled id must never crash the relay encoder: Unicode
    # digits pass str.isdigit() but not int() — falls back to sha, no raise
    assert 0 <= pw._hash64("²") < (1 << 63)


def test_sniffing_survives_large_envelope_headers():
    """Envelope weights frames with a JSON header over 64 KB (thousands of
    contributors) must still sniff as envelope — the check tolerates any
    header under 16 MB."""
    update = ModelUpdate(
        {"w": np.zeros(4, np.float32)},
        [f"10.0.{i // 256}.{i % 256}:40000" for i in range(4000)],  # ~80 KB header
        7,
    )
    data = encode_weights(WeightsEnvelope("src:1", 1, "add_model", update))
    hlen = int.from_bytes(data[:4], "little")
    assert hlen > (1 << 16)  # the header really is past the 64 KB boundary
    assert not pw.is_protobuf_weights(data)


def test_weights_roundtrip_and_sniffing():
    update = ModelUpdate({"w": np.arange(6.0, dtype=np.float32).reshape(2, 3)}, ["n1"], 42)
    env = WeightsEnvelope("src:1", 2, "add_model", update)
    data = pw.encode_weights_pb(env)
    assert pw.is_protobuf_weights(data)
    assert not pw.is_protobuf_weights(encode_weights(env))
    back = pw.decode_weights_pb(data)
    assert back.source == "src:1" and back.round == 2 and back.cmd == "add_model"
    assert back.update.contributors == ["n1"] and back.update.num_samples == 42
    assert back.update.encoded.startswith(b"P2TW")


def test_foreign_payload_rejected_not_unpickled():
    """A reference node's Weights.weights is a numpy pickle — refusing it
    (vs unpickling) is the documented security divergence."""
    import pickle

    pickled = pickle.dumps([np.zeros(4)])
    frame = pw.pb.Weights(
        source="ref:1", round=0, weights=pickled, contributors=["ref:1"],
        weight=1, cmd="add_model",
    ).SerializeToString()
    assert pw.is_protobuf_weights(frame)
    with pytest.raises(ValueError, match="P2TW"):
        pw.decode_weights_pb(frame)


def test_handshake_and_response_frames():
    data = pw.encode_handshake_pb("127.0.0.1:41234")
    assert pw.is_protobuf_handshake(data)
    assert not pw.is_protobuf_handshake(b"127.0.0.1:41234")  # raw addr frame
    assert pw.decode_handshake_pb(data) == "127.0.0.1:41234"
    assert pw.decode_response_ok_pb(pw.encode_response_pb(True))
    assert not pw.decode_response_ok_pb(pw.encode_response_pb(False, "nope"))


def test_degraded_mode_rejects_protobuf_frames(monkeypatch):
    """Without the protobuf runtime, a protobuf-looking frame must be
    REFUSED — decoding a HandShakeRequest as a raw UTF-8 address would
    register a garbage neighbor (b'\\n\\x0f127...' decodes fine) and
    poison the overlay."""
    proto = GrpcProtocol("127.0.0.1:0")
    frame = pw.encode_handshake_pb("127.0.0.1:41234")
    monkeypatch.setattr(pw, "HAVE_PROTOBUF", False)
    reply = proto.rpc_handshake(frame, None)
    assert b"protobuf runtime" in reply
    assert len(proto.neighbors.get_all()) == 0  # nothing registered
    # envelope frames still work in degraded mode
    reply = proto.rpc_handshake(b"127.0.0.1:41234", None)
    assert "127.0.0.1:41234" in proto.neighbors.get_all()


@pytest.mark.slow
def test_protobuf_federation_end_to_end():
    """The whole federation in WIRE_FORMAT='protobuf': every frame that
    crosses the real sockets is reference-schema protobuf, and the
    sniffing receivers converge exactly as the envelope format does.
    (Per-frame MIXED format is covered by the unit sniff tests — the
    format knob is process-global, so a true two-format two-node run in
    one process would race on it.)"""
    full = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    nodes = []
    try:
        Settings.WIRE_FORMAT = "protobuf"
        n0 = Node(
            learner=JaxLearner(mlp(seed=0), full.partition(0, 2), batch_size=64),
            protocol=GrpcProtocol("127.0.0.1:0"),
        )
        n0.start()
        nodes.append(n0)
        n1 = Node(
            learner=JaxLearner(mlp(seed=1), full.partition(1, 2), batch_size=64),
            protocol=GrpcProtocol("127.0.0.1:0"),
        )
        n1.start()
        nodes.append(n1)
        n0.connect(n1.addr)
        wait_convergence(nodes, 1, only_direct=True)
        n0.set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=90)
        check_equal_models(nodes)
        assert n0.learner.evaluate()["test_acc"] > 0.7
        # every frame that crossed the weight plane was protobuf
        assert n0.protocol.wire_stats["weights_msgs"] > 0
    finally:
        for n in nodes:
            n.stop()


# ---- real interop: the reference's own generated stubs ----
#
# These tests never touch proto_wire's encoders on the "foreign" side:
# frames are built and parsed by the reference's checked-in node_pb2 stubs
# and routed on the reference's literal method paths, so a path or schema
# regression cannot hide behind a self-referential round-trip (the round-3
# failure mode).

_REF_ROOT = "/root/reference"


def _ref_stubs():
    """Import the reference's generated protobuf/gRPC stubs, or skip."""
    if _REF_ROOT not in sys.path:
        sys.path.insert(0, _REF_ROOT)
    try:
        node_pb2 = importlib.import_module("p2pfl.communication.grpc.proto.node_pb2")
        node_pb2_grpc = importlib.import_module(
            "p2pfl.communication.grpc.proto.node_pb2_grpc"
        )
    except Exception as exc:  # noqa: BLE001 — absent outside the dev image
        pytest.skip(f"reference stubs unavailable: {exc!r}")
    return node_pb2, node_pb2_grpc


class _Probe:
    """Counting command handler for both control and weight planes."""

    def __init__(self, name="probe"):
        self.name = name
        self.calls = []

    def get_name(self):
        return self.name

    def execute(self, source, round, *args, update=None):
        self.calls.append((source, round, args, update))


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return cond()


def test_reference_method_paths_pinned():
    """Pin the reference's literal method strings so the route can never
    silently regress again (round 3 served only /p2pfl.NodeServices/ and a
    reference node got UNIMPLEMENTED on its very first RPC)."""
    from p2pfl_tpu.communication import grpc_transport as gt

    assert gt._SERVICE_REF == "/node.NodeServices/"
    proto = GrpcProtocol("127.0.0.1:0")
    routes = gt._Handler(proto)._routes
    for m in ("handshake", "disconnect", "send_message", "send_weights"):
        # the reference's stub paths (node_pb2_grpc.py uses these literals)
        assert f"/node.NodeServices/{m}" in routes
        # back-compat with existing repo federations
        assert f"/p2pfl.NodeServices/{m}" in routes
    # protobuf mode dials the reference path; envelope mode the native one
    Settings.WIRE_FORMAT = "protobuf"
    assert gt._svc() == "/node.NodeServices/"
    Settings.WIRE_FORMAT = "envelope"
    assert gt._svc() == "/p2pfl.NodeServices/"


@pytest.mark.slow
def test_reference_stub_drives_repo_node():
    """A repo server must complete handshake + send_message (with dedup +
    relay) + send_weights + disconnect when driven by the REFERENCE's
    generated stubs — the frames and paths a real reference node produces."""
    import grpc

    node_pb2, node_pb2_grpc = _ref_stubs()
    a = Node(protocol=GrpcProtocol("127.0.0.1:0"))
    b = Node(protocol=GrpcProtocol("127.0.0.1:0"))
    probe_a, probe_b = _Probe(), _Probe()
    a.protocol.add_command(probe_a)
    b.protocol.add_command(probe_b)
    a.start()
    b.start()
    channel = None
    try:
        a.connect(b.addr)
        assert _wait(lambda: b.addr in a.get_neighbors(only_direct=True))

        channel = grpc.insecure_channel(a.addr)
        stub = node_pb2_grpc.NodeServicesStub(channel)

        # handshake: reference stub -> repo server registers the peer
        resp = stub.handshake(
            node_pb2.HandShakeRequest(addr="10.9.8.7:1234"), timeout=5
        )
        assert not resp.HasField("error")
        assert "10.9.8.7:1234" in a.get_neighbors()

        # send_message: dispatched once, relayed to B, deduped on re-send
        frame = node_pb2.Message(
            source="10.9.8.7:1234", ttl=3, hash=424242, cmd="probe",
            args=["x", "y"], round=5,
        )
        resp = stub.send_message(frame, timeout=5)
        assert not resp.HasField("error")
        assert _wait(lambda: len(probe_a.calls) == 1)
        src, rnd, args, upd = probe_a.calls[0]
        assert (src, rnd, args, upd) == ("10.9.8.7:1234", 5, ("x", "y"), None)
        # TTL relay reaches B exactly once, carrying the same dedup hash
        assert _wait(lambda: len(probe_b.calls) == 1)
        # duplicate (same hash) is absorbed — ok reply, no re-dispatch
        resp = stub.send_message(frame, timeout=5)
        assert not resp.HasField("error")
        time.sleep(0.5)
        assert len(probe_a.calls) == 1 and len(probe_b.calls) == 1

        # send_weights: reference frame around a P2TW payload
        update = ModelUpdate(
            {"w": np.arange(4.0, dtype=np.float32)}, ["10.9.8.7:1234"], 17
        )
        resp = stub.send_weights(
            node_pb2.Weights(
                source="10.9.8.7:1234", round=5, weights=update.encode(),
                contributors=["10.9.8.7:1234"], weight=17, cmd="probe",
            ),
            timeout=5,
        )
        assert not resp.HasField("error")
        assert _wait(lambda: len(probe_a.calls) == 2)
        src, rnd, args, upd = probe_a.calls[1]
        assert src == "10.9.8.7:1234" and rnd == 5
        assert upd is not None and upd.num_samples == 17
        assert upd.contributors == ["10.9.8.7:1234"]

        # a pickled (reference-native) payload is refused, not unpickled
        import pickle

        resp = stub.send_weights(
            node_pb2.Weights(
                source="10.9.8.7:1234", round=5,
                weights=pickle.dumps([np.zeros(2)]),
                contributors=["10.9.8.7:1234"], weight=1, cmd="probe",
            ),
            timeout=5,
        )
        assert resp.HasField("error") and "malformed" in resp.error
        assert len(probe_a.calls) == 2  # nothing dispatched

        # disconnect: reference expects google.protobuf.Empty back — our
        # zero-byte no-error reply parses as exactly that. The target must
        # be a ROUTABLE peer — an unroutable fake would be evicted by
        # failed heartbeat sends before disconnect runs, making the removal
        # assertion vacuous — so register a third live repo node via the
        # reference stub, then disconnect it.
        c = Node(protocol=GrpcProtocol("127.0.0.1:0"))
        c.start()
        try:
            resp = stub.handshake(node_pb2.HandShakeRequest(addr=c.addr), timeout=5)
            assert not resp.HasField("error")
            assert c.addr in a.get_neighbors()
            stub.disconnect(node_pb2.HandShakeRequest(addr=c.addr), timeout=5)
            assert _wait(lambda: c.addr not in a.get_neighbors())
        finally:
            c.stop()
    finally:
        if channel is not None:
            channel.close()
        a.stop()
        b.stop()


@pytest.mark.slow
def test_repo_dials_reference_server():
    """The other direction: a repo node in WIRE_FORMAT='protobuf' must
    complete handshake/send_message/send_weights against a server built
    from the reference's OWN servicer registration (reference paths,
    reference deserializers)."""
    import grpc
    from concurrent import futures as cfutures

    node_pb2, node_pb2_grpc = _ref_stubs()
    from google.protobuf import empty_pb2

    received = {"handshake": [], "send_message": [], "send_weights": []}

    class _RefServicer(node_pb2_grpc.NodeServicesServicer):
        def handshake(self, request, context):
            received["handshake"].append(request.addr)
            return node_pb2.ResponseMessage()

        def disconnect(self, request, context):
            return empty_pb2.Empty()

        def send_message(self, request, context):
            received["send_message"].append(request)
            return node_pb2.ResponseMessage()

        def send_weights(self, request, context):
            received["send_weights"].append(request)
            return node_pb2.ResponseMessage()

    server = grpc.server(cfutures.ThreadPoolExecutor(max_workers=2))
    node_pb2_grpc.add_NodeServicesServicer_to_server(_RefServicer(), server)
    port = server.add_insecure_port("127.0.0.1:0")  # atomic bind, no TOCTOU
    assert port != 0
    ref_addr = f"127.0.0.1:{port}"
    server.start()

    Settings.WIRE_FORMAT = "protobuf"
    # the stub server never sends beats back, so on a loaded host the repo
    # node would heartbeat-evict it mid-test (HEARTBEAT_TIMEOUT=1.5s under
    # test settings) and the send asserts would flake — pin the timeout
    # high for the duration; the autouse settings fixture restores it
    saved_hb = Settings.HEARTBEAT_TIMEOUT
    Settings.HEARTBEAT_TIMEOUT = 60.0
    n = Node(protocol=GrpcProtocol("127.0.0.1:0"))
    n.start()
    try:
        # handshake travels the reference path and parses via its stub
        assert n.connect(ref_addr)
        assert _wait(lambda: received["handshake"] == [n.addr])

        # control frame: parsed by the reference deserializer, fields intact
        msg = Message(n.addr, "vote_train_set", ("cand", "3"), round=2, ttl=1)
        assert n.protocol.send(ref_addr, msg)
        # the heartbeater also streams "beat" frames here — select ours
        votes = lambda: [  # noqa: E731
            m for m in received["send_message"] if m.cmd == "vote_train_set"
        ]
        assert _wait(lambda: len(votes()) >= 1)
        got = votes()[0]
        assert got.source == n.addr and got.cmd == "vote_train_set"
        assert list(got.args) == ["cand", "3"] and got.round == 2

        # weights frame: reference-side parse sees contributors/weight/cmd
        update = ModelUpdate({"w": np.ones(3, np.float32)}, [n.addr], 9)
        env = WeightsEnvelope(n.addr, 2, "add_model", update)
        assert n.protocol.send(ref_addr, env)
        assert _wait(lambda: len(received["send_weights"]) >= 1)
        w = received["send_weights"][0]
        assert w.source == n.addr and w.round == 2 and w.cmd == "add_model"
        assert list(w.contributors) == [n.addr] and w.weight == 9
        assert w.weights.startswith(b"P2TW")
    finally:
        Settings.HEARTBEAT_TIMEOUT = saved_hb
        n.stop()
        server.stop(grace=0.2)
