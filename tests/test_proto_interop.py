"""Protobuf wire interop (``communication/proto_wire.py``).

The reference speaks generated-protobuf gRPC on
``/p2pfl.NodeServices/{handshake,disconnect,send_message,send_weights}``;
these tests pin (a) frame round-trips through the reference-schema
messages, (b) format sniffing — mixed envelope/protobuf federations
interoperate with no receiver configuration, (c) the documented security
divergence: foreign (non-P2TW) weight payloads are rejected, never
unpickled.
"""

import numpy as np
import pytest

from p2pfl_tpu.communication import proto_wire as pw
from p2pfl_tpu.communication.grpc_transport import (
    GrpcProtocol,
    encode_message,
    encode_weights,
)
from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import check_equal_models, wait_convergence, wait_to_finish

pytestmark = pytest.mark.skipif(not pw.HAVE_PROTOBUF, reason="protobuf runtime absent")


@pytest.fixture(autouse=True)
def _restore_format():
    yield
    Settings.WIRE_FORMAT = "envelope"


def test_message_roundtrip_and_sniffing():
    msg = Message("1.2.3.4:5", "vote_train_set", ("a", "1"), round=3, ttl=7)
    data = pw.encode_message_pb(msg)
    assert pw.is_protobuf_message(data)
    assert not pw.is_protobuf_message(encode_message(msg))  # JSON starts '{'
    back = pw.decode_message_pb(data)
    assert (back.source, back.cmd, back.args, back.round, back.ttl) == (
        msg.source, msg.cmd, msg.args, msg.round, msg.ttl
    )
    # the reference's int64 hash carries dedup identity: stable across hops
    assert back.msg_id == pw.decode_message_pb(data).msg_id
    # unset optional round maps to our -1 sentinel
    no_round = pw.decode_message_pb(pw.encode_message_pb(Message("s", "beat")))
    assert no_round.round == -1


def test_relay_keeps_dedup_hash_stable():
    """A relayed protobuf message must carry the SAME int64 hash on every
    hop — re-hashing per hop would defeat gossip dedup entirely (each
    receiver would dispatch the same command once per hop). Reference
    nodes use Python's SIGNED hash, so negative values round-trip too."""
    msg = Message("n1:1", "vote_train_set", ("a", "1"), round=0, ttl=5)
    hop1 = pw.decode_message_pb(pw.encode_message_pb(msg))
    hop2 = pw.decode_message_pb(pw.encode_message_pb(hop1))  # the relay
    assert hop1.msg_id == hop2.msg_id

    for h in (-1234, -(1 << 63), (1 << 63) - 1):  # incl. the int64 extremes
        neg = pw.pb.Message(source="ref:1", ttl=5, hash=h, cmd="beat").SerializeToString()
        ref_hop1 = pw.decode_message_pb(neg)
        ref_hop2 = pw.decode_message_pb(pw.encode_message_pb(ref_hop1))
        assert ref_hop1.msg_id == ref_hop2.msg_id == str(h)

    # a peer-controlled id must never crash the relay encoder: Unicode
    # digits pass str.isdigit() but not int() — falls back to sha, no raise
    assert 0 <= pw._hash64("²") < (1 << 63)


def test_sniffing_survives_large_envelope_headers():
    """Envelope weights frames with a JSON header over 64 KB (thousands of
    contributors) must still sniff as envelope — the check tolerates any
    header under 16 MB."""
    update = ModelUpdate(
        {"w": np.zeros(4, np.float32)},
        [f"10.0.{i // 256}.{i % 256}:40000" for i in range(4000)],  # ~80 KB header
        7,
    )
    data = encode_weights(WeightsEnvelope("src:1", 1, "add_model", update))
    hlen = int.from_bytes(data[:4], "little")
    assert hlen > (1 << 16)  # the header really is past the 64 KB boundary
    assert not pw.is_protobuf_weights(data)


def test_weights_roundtrip_and_sniffing():
    update = ModelUpdate({"w": np.arange(6.0, dtype=np.float32).reshape(2, 3)}, ["n1"], 42)
    env = WeightsEnvelope("src:1", 2, "add_model", update)
    data = pw.encode_weights_pb(env)
    assert pw.is_protobuf_weights(data)
    assert not pw.is_protobuf_weights(encode_weights(env))
    back = pw.decode_weights_pb(data)
    assert back.source == "src:1" and back.round == 2 and back.cmd == "add_model"
    assert back.update.contributors == ["n1"] and back.update.num_samples == 42
    assert back.update.encoded.startswith(b"P2TW")


def test_foreign_payload_rejected_not_unpickled():
    """A reference node's Weights.weights is a numpy pickle — refusing it
    (vs unpickling) is the documented security divergence."""
    import pickle

    pickled = pickle.dumps([np.zeros(4)])
    frame = pw.pb.Weights(
        source="ref:1", round=0, weights=pickled, contributors=["ref:1"],
        weight=1, cmd="add_model",
    ).SerializeToString()
    assert pw.is_protobuf_weights(frame)
    with pytest.raises(ValueError, match="P2TW"):
        pw.decode_weights_pb(frame)


def test_handshake_and_response_frames():
    data = pw.encode_handshake_pb("127.0.0.1:41234")
    assert pw.is_protobuf_handshake(data)
    assert not pw.is_protobuf_handshake(b"127.0.0.1:41234")  # raw addr frame
    assert pw.decode_handshake_pb(data) == "127.0.0.1:41234"
    assert pw.decode_response_ok_pb(pw.encode_response_pb(True))
    assert not pw.decode_response_ok_pb(pw.encode_response_pb(False, "nope"))


def test_degraded_mode_rejects_protobuf_frames(monkeypatch):
    """Without the protobuf runtime, a protobuf-looking frame must be
    REFUSED — decoding a HandShakeRequest as a raw UTF-8 address would
    register a garbage neighbor (b'\\n\\x0f127...' decodes fine) and
    poison the overlay."""
    proto = GrpcProtocol("127.0.0.1:0")
    frame = pw.encode_handshake_pb("127.0.0.1:41234")
    monkeypatch.setattr(pw, "HAVE_PROTOBUF", False)
    reply = proto.rpc_handshake(frame, None)
    assert b"protobuf runtime" in reply
    assert len(proto.neighbors.get_all()) == 0  # nothing registered
    # envelope frames still work in degraded mode
    reply = proto.rpc_handshake(b"127.0.0.1:41234", None)
    assert "127.0.0.1:41234" in proto.neighbors.get_all()


@pytest.mark.slow
def test_protobuf_federation_end_to_end():
    """The whole federation in WIRE_FORMAT='protobuf': every frame that
    crosses the real sockets is reference-schema protobuf, and the
    sniffing receivers converge exactly as the envelope format does.
    (Per-frame MIXED format is covered by the unit sniff tests — the
    format knob is process-global, so a true two-format two-node run in
    one process would race on it.)"""
    full = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    nodes = []
    try:
        Settings.WIRE_FORMAT = "protobuf"
        n0 = Node(
            learner=JaxLearner(mlp(seed=0), full.partition(0, 2), batch_size=64),
            protocol=GrpcProtocol("127.0.0.1:0"),
        )
        n0.start()
        nodes.append(n0)
        n1 = Node(
            learner=JaxLearner(mlp(seed=1), full.partition(1, 2), batch_size=64),
            protocol=GrpcProtocol("127.0.0.1:0"),
        )
        n1.start()
        nodes.append(n1)
        n0.connect(n1.addr)
        wait_convergence(nodes, 1, only_direct=True)
        n0.set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=90)
        check_equal_models(nodes)
        assert n0.learner.evaluate()["test_acc"] > 0.7
        # every frame that crossed the weight plane was protobuf
        assert n0.protocol.wire_stats["weights_msgs"] > 0
    finally:
        for n in nodes:
            n.stop()
