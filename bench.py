"""Headline benchmark: 64-node federated MNIST, time to 98% test accuracy.

BASELINE.md north star: 64 federated MNIST nodes converge to >=98% test
accuracy in <60 s wall-clock with zero gRPC traffic (weights over ICI).
The reference publishes no numbers (SURVEY §6); the target is the driver's
BASELINE.json bound, so ``vs_baseline = 60 / measured_seconds`` (>1 beats it).

Honesty notes (VERDICT r1 #2):
- the JSON records data provenance (``data``: "idx" = real MNIST files,
  "synthetic-hard" = the Gaussian-mixture stand-in);
- the synthetic task uses 8 prototype modes per class at prototype scale
  0.5 / noise 0.7 — measured to need ~12 federated rounds to 98% (see
  ``accuracy_curve``), so "time-to-98%" measures convergence, not the
  latency of one dispatch;
- ``mfu`` is model-FLOPs-utilization of the steady-state round (compiled
  XLA FLOPs / wall-clock / chip peak), null off-TPU.

Runs the SPMD federation on whatever devices are available (the real TPU
chip under the driver; the virtual CPU mesh under tests). One compile
warm-up phase runs first and is excluded — state is fully reset afterwards.

Prints exactly ONE JSON line on stdout; progress goes to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np


N_NODES = 64
TARGET_ACC = 0.98
TARGET_SECONDS = 60.0
MAX_ROUNDS = 30
CHUNK = 5  # rounds per fused dispatch (train + eval curve on device)
BATCH = 64
# Gaussian-mixture difficulty (measured: ~12 rounds to 98% at this setting)
HARD_TASK = {"modes": 8, "noise": 0.7, "proto_scale": 0.5}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.management.profiling import force_execution, mfu
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import SpmdFederation

    log(f"devices: {jax.devices()}")
    data = FederatedDataset.mnist(os.environ.get("P2PFL_MNIST_DIR"), **HARD_TASK)
    provenance = "idx" if data.source == "idx" else "synthetic-hard"
    log(f"data: {provenance}")
    model = mlp()

    # keep_opt_state: the framework's documented improvement over the
    # reference's per-round optimizer reset (Adam moments carry across
    # rounds) — measured 12 -> 9 rounds to 98% on this task; recorded in
    # the JSON so the knob is visible
    fed = SpmdFederation.from_dataset(
        model, data, n_nodes=N_NODES, batch_size=BATCH, vote=False, seed=3,
        keep_opt_state=True,
    )

    # compile warm-up, then reset state in place (same mesh → same
    # executables). Both fused variants (eval curve + steady state) and the
    # single-round program are warmed; a D2H fetch is the only thing that
    # truly forces execution on some remote-attached platforms.
    t0 = time.monotonic()
    # eval chunk twice: round-1 (fresh) and rounds>=2 (evolved) input
    # layouts compile separately — one warm call would leave the second
    # timed chunk to compile inside the timer
    [float(e["test_acc"]) for e in fed.run_fused(CHUNK, epochs=1, eval=True)]
    [float(e["test_acc"]) for e in fed.run_fused(CHUNK, epochs=1, eval=True)]
    fed.run_fused(CHUNK, epochs=1)  # steady-state variant
    float(fed.evaluate()["test_acc"])
    log(f"warm-up (compile, {3 * CHUNK} rounds): {time.monotonic() - t0:.1f}s")
    t0 = time.monotonic()
    fed.reset(seed=3)
    force_execution(fed.params)
    log(f"reset: {time.monotonic() - t0:.2f}s")

    # convergence: fused chunks of CHUNK rounds, the whole chunk (train +
    # per-round eval of the aggregated model) is ONE dispatch; the accuracy
    # curve syncs once per chunk instead of once per round
    t0 = time.monotonic()
    elapsed = float("nan")
    acc = 0.0
    curve = []
    while len(curve) < MAX_ROUNDS:
        entries = fed.run_fused(CHUNK, epochs=1, eval=True)
        accs = [float(e["test_acc"]) for e in entries]
        elapsed = time.monotonic() - t0
        curve.extend(round(a, 4) for a in accs)
        log(f"rounds {len(curve) - CHUNK + 1}-{len(curve)}: acc={accs} elapsed={elapsed:.2f}s")
        if max(accs) >= TARGET_ACC:
            acc = max(accs)
            break
        acc = accs[-1]

    if acc < TARGET_ACC:
        # did not reach target: report elapsed at best acc, flagged by value
        log(f"target {TARGET_ACC} not reached (best {acc:.4f})")
    rounds_to_target = next(
        (i + 1 for i, a in enumerate(curve) if a >= TARGET_ACC), len(curve)
    )

    # steady-state throughput: one more fused span, no eval (CHUNK-shaped —
    # the only fused programs warm-up compiled; any other span length would
    # put a fresh XLA compile inside the timer)
    t1 = time.monotonic()
    fed.run_fused(CHUNK, epochs=1)
    force_execution(fed.params)
    sec_per_round = (time.monotonic() - t1) / CHUNK

    # MFU of the steady-state round (train only, no eval)
    flops = fed.round_flops()
    round_mfu = mfu(flops, sec_per_round, n_devices=len(set(fed.mesh.devices.flat)))

    print(
        json.dumps(
            {
                "metric": "mnist64_time_to_98pct",
                "value": round(elapsed, 3),
                "unit": "s",
                "vs_baseline": round(TARGET_SECONDS / elapsed, 3) if np.isfinite(elapsed) else 0.0,
                "reached_acc": round(acc, 4),
                "rounds_to_target": rounds_to_target,
                "accuracy_curve": curve,
                "sec_per_round": round(sec_per_round, 4),
                "flops_per_round": flops,
                "mfu": round(round_mfu, 4) if round_mfu is not None else None,
                "data": provenance,
                "n_nodes": N_NODES,
                "keep_opt_state": True,
                "devices": len(jax.devices()),
            }
        )
    )


if __name__ == "__main__":
    main()
