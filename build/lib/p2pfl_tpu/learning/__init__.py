"""Learning layer: weights containers, codecs, learners, aggregators."""

from p2pfl_tpu.learning.weights import ModelUpdate, decode_params, encode_params

__all__ = ["ModelUpdate", "decode_params", "encode_params"]
