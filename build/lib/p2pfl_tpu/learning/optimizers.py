"""Optimizer constructors with shared identity.

jit caches key on the optax transform's *object identity* (it's a NamedTuple
of closures), so two learners calling ``optax.adam(1e-3)`` independently
would compile every train step twice. These constructors are lru-cached —
same config → same object → one compilation across all nodes of a
federation. The reference exposes Adam only (hardcoded in its Lightning
modules, ``mnist_examples/models/mlp.py``).
"""

from __future__ import annotations

from functools import lru_cache

import optax


@lru_cache(maxsize=None)
def adam(lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999) -> optax.GradientTransformation:
    return optax.adam(lr, b1=b1, b2=b2)


@lru_cache(maxsize=None)
def adamw(lr: float = 1e-3, weight_decay: float = 1e-4) -> optax.GradientTransformation:
    return optax.adamw(lr, weight_decay=weight_decay)


@lru_cache(maxsize=None)
def sgd(lr: float = 1e-2, momentum: float = 0.9, nesterov: bool = False) -> optax.GradientTransformation:
    return optax.sgd(lr, momentum=momentum, nesterov=nesterov)


@lru_cache(maxsize=None)
def adam_cosine(
    lr: float = 1e-3, decay_steps: int = 10_000, warmup_steps: int = 100
) -> optax.GradientTransformation:
    """Adam with linear warmup + cosine decay (the standard LM recipe)."""
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=lr, warmup_steps=warmup_steps, decay_steps=decay_steps
    )
    return optax.adam(schedule)


@lru_cache(maxsize=None)
def clipped(name: str = "adam", lr: float = 1e-3, max_norm: float = 1.0) -> optax.GradientTransformation:
    """Global-norm gradient clipping around a base optimizer."""
    base = {"adam": adam, "adamw": adamw, "sgd": sgd}[name](lr)
    return optax.chain(optax.clip_by_global_norm(max_norm), base)
