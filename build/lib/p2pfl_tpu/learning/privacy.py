"""Differential privacy: DP-SGD local steps + an RDP accountant.

The reference has no privacy mechanism anywhere (grep for clip/noise/dp
finds nothing); in federated learning DP-SGD (Abadi et al. 2016) is the
standard defense against gradient-leakage of client data, so the rebuild
ships it as a first-class learner knob.

Mechanics (``dp_train_epoch`` / the ``dp_clip``/``dp_noise`` knobs):

- per-example gradients via ``jax.vmap`` of a single-example loss grad —
  on TPU this is a batched program, not a Python loop;
- each example's gradient is clipped to L2 norm ``clip``;
- Gaussian noise ``N(0, (noise · clip)² / B²)`` is added to the mean.

Accounting (``PrivacyAccountant``): Rényi differential privacy of the
subsampled Gaussian mechanism, the analytical moments-accountant bound for
integer orders α (Abadi et al. 2016 lemma 3 / Mironov 2017):

    RDP(α) ≤ 1/(α−1) · log Σ_{k=0..α} C(α,k)(1−q)^{α−k} q^k e^{k(k−1)/2σ²}

composed linearly over steps, converted to (ε, δ) by
``ε = min_α RDP(α)·T + log(1/δ)/(α−1)``. Pure numpy, no dependencies.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

Pytree = object


def clip_by_global_norm(grads: Pytree, clip: float) -> Pytree:
    """Scale ``grads`` so its global L2 norm is at most ``clip``."""
    norm = jnp.sqrt(
        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(grads))
    )
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def dp_grads(loss_one, params, x, y, clip: float, noise: float, key, remat: bool = False):
    """Per-example clipped + noised mean gradient (the DP-SGD estimator).

    ``loss_one(params, x_i, y_i) -> scalar`` is the single-example loss;
    ``x``/``y`` carry the batch dim. ``remat`` rematerializes each
    example's backward (per-example grads store activations for the whole
    batch otherwise — the HBM↔FLOPs trade big models need). Returns
    ``(grads, mean_loss)`` — the pre-update loss comes free from the grad
    pass, matching what the non-DP paths report.
    """
    batch = x.shape[0]
    if remat:
        loss_one = jax.checkpoint(loss_one)

    def one(xi, yi):
        loss, g = jax.value_and_grad(loss_one)(params, xi, yi)
        return clip_by_global_norm(g, clip), loss

    per_ex, losses = jax.vmap(one)(x, y)  # [B, ...] pytrees, [B] losses
    mean_g = jax.tree.map(lambda g: jnp.mean(g.astype(jnp.float32), axis=0), per_ex)
    leaves, tdef = jax.tree.flatten(mean_g)
    keys = jax.random.split(key, len(leaves))
    sigma = noise * clip / batch
    noised = [
        (g + sigma * jax.random.normal(k, g.shape, jnp.float32)).astype(p.dtype)
        for g, k, p in zip(leaves, keys, jax.tree.leaves(params))
    ]
    return tdef.unflatten(noised), jnp.mean(losses)


@partial(jax.jit, static_argnames=("module", "tx", "clip", "noise", "prox_mu"))
def dp_train_epoch(
    params, opt_state, xs, ys, key, module, tx, clip: float, noise: float,
    prox_mu: float = 0.0, anchor=None,
):
    """One DP-SGD epoch: scan over [nb, bs, ...] batches (counterpart of
    ``learner.train_epoch`` with the DP estimator instead of the batch
    gradient; ``prox_mu`` keeps FedProx active under DP, same as the SPMD
    path)."""
    import optax

    from p2pfl_tpu.learning.learner import _loss, _prox_term

    def loss_one(p, xi, yi):
        loss = _loss(p, module, xi[None], yi[None])[0]
        if prox_mu > 0.0:
            loss = loss + _prox_term(p, anchor, prox_mu)
        return loss

    def step(carry, batch):
        p, o, k = carry
        x, y = batch
        k, sub = jax.random.split(k)
        grads, loss = dp_grads(loss_one, p, x, y, clip, noise, sub)
        updates, o = tx.update(grads, o, p)
        p = optax.apply_updates(p, updates)
        return (p, o, k), loss

    (params, opt_state, _), losses = jax.lax.scan(step, (params, opt_state, key), (xs, ys))
    return params, opt_state, jnp.mean(losses)


class PrivacyAccountant:
    """(ε, δ) tracking for the subsampled Gaussian mechanism.

    ``q`` = batch/shard sampling rate, ``noise`` = noise multiplier σ.
    ``step(n)`` records n mechanism invocations (one per DP-SGD step).
    """

    ORDERS = tuple(range(2, 65))

    def __init__(self, noise: float, q: float) -> None:
        if noise <= 0 or not 0 < q <= 1:
            raise ValueError("need noise > 0 and 0 < q <= 1")
        self.noise = noise
        self.q = q
        self.steps = 0
        self._rdp_per_step = [self._rdp_one(a) for a in self.ORDERS]

    def _rdp_one(self, alpha: int) -> float:
        """RDP of ONE subsampled-Gaussian step at integer order ``alpha``."""
        q, sigma = self.q, self.noise
        if q == 1.0:
            return alpha / (2.0 * sigma**2)
        # log Σ_k C(α,k) (1−q)^{α−k} q^k exp(k(k−1)/2σ²), stable in log-space
        log_terms = [
            math.lgamma(alpha + 1)
            - math.lgamma(k + 1)
            - math.lgamma(alpha - k + 1)
            + (alpha - k) * math.log1p(-q)
            + k * math.log(q)
            + (k * (k - 1)) / (2.0 * sigma**2)
            for k in range(alpha + 1)
        ]
        m = max(log_terms)
        return (m + math.log(sum(math.exp(t - m) for t in log_terms))) / (alpha - 1)

    def step(self, n: int = 1) -> None:
        self.steps += n

    def epsilon(self, delta: float = 1e-5) -> float:
        """Smallest ε over the tracked orders for the given δ."""
        if self.steps == 0:
            return 0.0
        return min(
            r * self.steps + math.log(1.0 / delta) / (a - 1)
            for a, r in zip(self.ORDERS, self._rdp_per_step)
        )
