"""FedAvg: sample-weighted mean (McMahan et al. 2017).

Reference: ``p2pfl/learning/aggregators/fedavg.py:28-60`` (a Python loop over
state-dict layers). Here: one jitted weighted-mean over the stacked pytree.
"""

from __future__ import annotations

import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.ops.aggregation import fedavg
from p2pfl_tpu.ops.tree import tree_stack
from p2pfl_tpu.settings import Settings


class FedAvg(Aggregator):
    SUPPORTS_PARTIALS = True
    MASK_COMPATIBLE = True  # linear: secagg pairwise masks cancel through it

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        stacked = tree_stack([m.params for m in models])
        weights = jnp.asarray([float(m.num_samples) for m in models])
        params = fedavg(stacked, weights, Settings.AGG_DTYPE)
        contributors = sorted({c for m in models for c in m.contributors})
        return ModelUpdate(params, contributors, sum(m.num_samples for m in models))
