"""FedMedian: coordinate-wise median (Yin et al. 2018). Robust aggregator."""

from __future__ import annotations

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.ops.aggregation import fedmedian
from p2pfl_tpu.ops.tree import tree_stack


class FedMedian(Aggregator):
    # medians over pre-averaged partials are not medians over models
    SUPPORTS_PARTIALS = False

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        params = fedmedian(tree_stack([m.params for m in models]))
        contributors = sorted({c for m in models for c in m.contributors})
        return ModelUpdate(params, contributors, sum(m.num_samples for m in models))
