"""Aggregation strategies over jax.Array pytrees."""

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.aggregators.bulyan import Bulyan
from p2pfl_tpu.learning.aggregators.clipping import CenteredClip
from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
from p2pfl_tpu.learning.aggregators.fedmedian import FedMedian
from p2pfl_tpu.learning.aggregators.fedopt import FedAdagrad, FedAdam, FedOpt, FedYogi
from p2pfl_tpu.learning.aggregators.krum import Krum
from p2pfl_tpu.learning.aggregators.trimmed_mean import TrimmedMean

__all__ = [
    "Aggregator",
    "Bulyan",
    "CenteredClip",
    "FedAdagrad",
    "FedAdam",
    "FedAvg",
    "FedMedian",
    "FedOpt",
    "FedYogi",
    "Krum",
    "TrimmedMean",
]
