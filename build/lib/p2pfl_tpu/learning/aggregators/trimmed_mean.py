"""Coordinate-wise trimmed mean (Yin et al. 2018). Robust aggregator."""

from __future__ import annotations

import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.ops.aggregation import fedavg, trimmed_mean
from p2pfl_tpu.ops.tree import tree_stack


class TrimmedMean(Aggregator):
    SUPPORTS_PARTIALS = False

    def __init__(self, node_name: str = "unknown", trim: int = 1) -> None:
        super().__init__(node_name)
        self.trim = trim

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        n = len(models)
        trim = min(self.trim, max((n - 1) // 2, 0))
        stacked = tree_stack([m.params for m in models])
        if trim > 0:
            params = trimmed_mean(stacked, trim)
        else:  # not enough models to trim — plain unweighted mean
            params = fedavg(stacked, jnp.ones(n))
        contributors = sorted({c for m in models for c in m.contributors})
        return ModelUpdate(params, contributors, sum(m.num_samples for m in models))
