"""(Multi-)Krum (Blanchard et al. 2017). Byzantine-robust selection.

The pairwise-distance matrix is computed as a single [N, P] @ [P, N] matmul
on the MXU (``ops/aggregation.py:krum_select``) rather than a nested python
loop over model pairs.
"""

from __future__ import annotations

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.ops.aggregation import krum
from p2pfl_tpu.ops.tree import tree_stack


class Krum(Aggregator):
    SUPPORTS_PARTIALS = False

    def __init__(self, node_name: str = "unknown", n_byzantine: int = 1, multi: int = 1) -> None:
        super().__init__(node_name)
        self.n_byzantine = n_byzantine
        self.multi = multi

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        stacked = tree_stack([m.params for m in models])
        params = krum(stacked, self.n_byzantine, min(self.multi, len(models)))
        contributors = sorted({c for m in models for c in m.contributors})
        return ModelUpdate(params, contributors, sum(m.num_samples for m in models))
