"""Bulyan (El Mhamdi et al. 2018): Krum selection + trimmed-mean aggregation.

Stronger than either alone: Krum bounds the attacker to models near honest
ones, the trimmed mean then removes per-coordinate outliers those survivors
still carry ("a little is enough" attacks). Needs N ≥ 4f + 3.

The reference ships FedAvg only (``p2pfl/learning/aggregators/fedavg.py``);
this completes the Byzantine-robust family (median / trimmed-mean / Krum /
Bulyan) for BASELINE config 4.
"""

from __future__ import annotations

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.ops.aggregation import bulyan
from p2pfl_tpu.ops.tree import tree_stack


class Bulyan(Aggregator):
    SUPPORTS_PARTIALS = False  # needs the individual models, like Krum

    def __init__(self, node_name: str = "unknown", n_byzantine: int = 1) -> None:
        super().__init__(node_name)
        self.n_byzantine = n_byzantine

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        stacked = tree_stack([m.params for m in models])
        params = bulyan(stacked, self.n_byzantine)
        contributors = sorted({c for m in models for c in m.contributors})
        return ModelUpdate(params, contributors, sum(m.num_samples for m in models))
