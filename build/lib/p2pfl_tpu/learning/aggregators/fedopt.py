"""FedOpt family: server-side adaptive optimizers (Reddi et al. 2021).

The round's FedAvg result is not taken as the new model directly; instead
``prev_global - fedavg`` becomes a pseudo-gradient and a server optimizer
(Adam / Yogi / Adagrad) steps the global model — markedly faster under
heterogeneous (non-IID) shards.

Decentralized caveat: the "server" state (moments + previous global) lives
on every aggregating node. States stay identical across nodes as long as
the train set is stable — which is the default round semantics inherited
from the reference (voting happens only in round 0,
``round_finished_stage.py:69-70``). With ``Settings.VOTE_EVERY_ROUND=True``
a node newly elected mid-experiment starts with fresh moments and will
disagree with its peers for a few rounds (warned once at aggregate time).

The reference ships no adaptive server optimizer (FedAvg only,
``p2pfl/learning/aggregators/fedavg.py``); its docs list Scaffold as
"coming soon" (``docs/source/library_design.md``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.ops.aggregation import fedavg, fedopt_update
from p2pfl_tpu.ops.tree import tree_stack
from p2pfl_tpu.settings import Settings


class FedOpt(Aggregator):
    """FedAvg + server-side adaptive step. Subclasses pin the optimizer.

    ``SUPPORTS_PARTIALS = False``: the server step is nonlinear AND
    stateful, so ``aggregate`` must run exactly once per round on the full
    model set — feeding it gossip partials would advance the moments
    mid-round and emit server-stepped payloads that peers would re-average
    as if they were plain means. Peers therefore gossip individual models
    (``get_models_to_send``), like the robust family.
    """

    SUPPORTS_PARTIALS = False
    ALWAYS_AGGREGATE = True  # single-update shortcut must not skip the step
    SERVER_OPT = "adam"

    def __init__(
        self,
        node_name: str = "unknown",
        server_lr: float = 0.1,
        beta1: float = 0.9,
        beta2: float = 0.99,
        tau: float = 1e-3,
    ) -> None:
        super().__init__(node_name)
        self.server_lr = server_lr
        self.beta1 = beta1
        self.beta2 = beta2
        self.tau = tau
        self._prev = None  # previous global params (the server's x_t)
        self._m = None
        self._v = None
        self._t = 0
        self._warned = False

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        stacked = tree_stack([m.params for m in models])
        weights = jnp.asarray([float(m.num_samples) for m in models])
        avg = fedavg(stacked, weights, Settings.AGG_DTYPE)
        contributors = sorted({c for m in models for c in m.contributors})
        total = sum(m.num_samples for m in models)

        if self._prev is None:
            # round 0: adopt the average and start server state from it
            if Settings.VOTE_EVERY_ROUND and not self._warned:
                self._warned = True
                logger.warning(
                    self.node_name,
                    "FedOpt with per-round voting: newly elected nodes start "
                    "with fresh server moments and briefly diverge from peers",
                )
            self._prev = avg
            self._m = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), avg)
            self._v = jax.tree.map(lambda x: jnp.zeros_like(x, jnp.float32), avg)
            return ModelUpdate(avg, contributors, total)

        self._t += 1
        new, self._m, self._v = fedopt_update(
            self._prev,
            avg,
            self._m,
            self._v,
            jnp.float32(self._t),
            opt=self.SERVER_OPT,
            lr=self.server_lr,
            b1=self.beta1,
            b2=self.beta2,
            tau=self.tau,
        )
        self._prev = new
        return ModelUpdate(new, contributors, total)


    def on_result(self, update: ModelUpdate) -> ModelUpdate:
        # the round resolved to a peer's (already server-stepped) aggregate
        # without this node aggregating: adopt it as the server's x_t so the
        # next round's pseudo-gradient is computed against the consensus
        # global, not a stale one. Moments must exist too — a node whose
        # FIRST round resolves this way would otherwise crash in
        # fedopt_update when it later aggregates itself.
        self._prev = update.params
        if self._m is None:
            self._m = jax.tree.map(
                lambda x: jnp.zeros_like(x, jnp.float32), update.params
            )
            self._v = jax.tree.map(
                lambda x: jnp.zeros_like(x, jnp.float32), update.params
            )
        return update


    def reset_experiment(self) -> None:
        # same staleness hazard as CenteredClip's center: a new experiment
        # must not server-step its round 0 against the previous
        # experiment's final global, nor inherit its moments
        self._prev = None
        self._m = None
        self._v = None
        self._t = 0


class FedAdam(FedOpt):
    SERVER_OPT = "adam"


class FedYogi(FedOpt):
    SERVER_OPT = "yogi"


class FedAdagrad(FedOpt):
    SERVER_OPT = "adagrad"
