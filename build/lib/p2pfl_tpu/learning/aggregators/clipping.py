"""Centered clipping (Karimireddy, He & Jaggi 2021). Robust aggregator.

History-aware Byzantine robustness: starting from the previous round's
global model ``v``, iterate ``v ← v + mean_i clip_τ(x_i − v)`` — each
node's whole-model deviation is rescaled to norm ≤ τ, so an attacker can
displace the aggregate by at most τ per round regardless of magnitude.
Complements the existing family: needs no Byzantine-count estimate
(trimmed mean/Krum/Bulyan do), and uses every honest node's information
(Krum discards all but the selected). The reference ships FedAvg only
(``p2pfl/learning/aggregators/fedavg.py``).
"""

from __future__ import annotations

from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.ops.aggregation import centered_clip, fedmedian
from p2pfl_tpu.ops.tree import tree_stack


class CenteredClip(Aggregator):
    """``SUPPORTS_PARTIALS = False``: clipping is nonlinear per node, so
    pre-averaged gossip partials would launder an attacker's model into a
    partial mean the clip can no longer bound; peers gossip individual
    models instead (``get_models_to_send``), like the rest of the robust
    family. Stateful like FedOpt: the clip center is the previous round's
    global model, resynced via :meth:`on_result` when a peer's finished
    aggregate arrives first."""

    SUPPORTS_PARTIALS = False
    ALWAYS_AGGREGATE = True  # center must advance exactly once per round

    def __init__(
        self, node_name: str = "unknown", tau: float = 1.0, iters: int = 3
    ) -> None:
        super().__init__(node_name)
        if tau <= 0:
            # tau <= 0 zeroes every clip factor — the aggregate would never
            # leave the center and training silently freezes
            raise ValueError(f"tau must be > 0 (got {tau})")
        if iters < 1:
            raise ValueError(f"iters must be >= 1 (got {iters})")
        self.tau = float(tau)
        self.iters = int(iters)
        self._center = None  # previous round's global model

    def aggregate(self, models: list[ModelUpdate]) -> ModelUpdate:
        stacked = tree_stack([m.params for m in models])
        contributors = sorted({c for m in models for c in m.contributors})
        total = sum(m.num_samples for m in models)
        center = self._center
        if center is None:
            # round 0: no history to clip against — bootstrap with the
            # coordinate-wise median (a mean would hand a round-0 attacker
            # the center; the paper's v_0 is arbitrary, so pick the robust
            # option) and still clip around it
            center = fedmedian(stacked)
        params = centered_clip(stacked, center, self.tau, self.iters)
        self._center = params
        return ModelUpdate(params, contributors, total)

    def on_result(self, update: ModelUpdate) -> ModelUpdate:
        # consensus aggregate arrived from a peer: adopt it as the next
        # round's clip center
        self._center = update.params
        return update

    def reset_experiment(self) -> None:
        # a second experiment on the same node must re-bootstrap from the
        # median, not clip round 0 against the previous experiment's final
        # model (which would pin early progress to tau per round)
        self._center = None
