#!/bin/sh
# Build the native codec shared library next to this script.
set -e
cd "$(dirname "$0")"
g++ -O3 -shared -fPIC -o libp2tw.so codec.cpp
echo "built $(pwd)/libp2tw.so"
