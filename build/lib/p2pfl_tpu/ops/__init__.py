"""Pure JAX ops: pytree math, aggregation kernels, codecs."""

from p2pfl_tpu.ops.tree import (
    tree_add,
    tree_scale,
    tree_stack,
    tree_sub,
    tree_unstack,
    tree_weighted_mean,
    tree_zeros_like,
)

__all__ = [
    "tree_add",
    "tree_scale",
    "tree_stack",
    "tree_sub",
    "tree_unstack",
    "tree_weighted_mean",
    "tree_zeros_like",
]
