"""Framework exceptions.

Reference equivalents: ``p2pfl/exceptions.py:21-36``,
``p2pfl/learning/exceptions.py:21-31``,
``p2pfl/communication/exceptions.py:20``.
"""


class NodeRunningException(Exception):
    """Raised when an operation requires the node to be stopped (or vice versa)."""


class LearnerNotSetException(Exception):
    """Raised when a learning operation runs before a learner exists."""


class ZeroRoundsException(Exception):
    """Raised when learning is started with zero rounds."""


class DecodingParamsError(Exception):
    """Raised when a serialized weights payload cannot be decoded."""


class ModelNotMatchingError(Exception):
    """Raised when received parameters do not match the local model structure."""


class NeighborNotConnectedError(Exception):
    """Raised when sending to a neighbor that is not connected."""


class AnchorMismatchError(Exception):
    """Raised when a delta-coded (topk8) payload references a different
    round-start anchor than the receiver holds.

    NOT a fatal decode error: the receiver ignores the update and waits for
    one it can reconstruct (a stale node catches up via a later dense or
    matching-anchor payload), unlike :class:`DecodingParamsError` which
    stops the node (reference ``add_model_command.py:96-104``).
    """


class SecAggError(Exception):
    """Raised when a secure-aggregation contribution cannot be masked safely.

    The caller must NOT fall back to sending the model unmasked: peers that
    already derived this node's pair seeds would still add their half of the
    pairwise masks, which then never cancel — silently turning the round's
    aggregate into noise. Skipping the contribution instead leaves coverage
    incomplete, which the aggregator detects and reports loudly.
    """


class CommunicationError(Exception):
    """Raised on transport-level send failures."""
