"""Privacy/bandwidth demo: secure aggregation OR compressed gossip.

Beyond-reference capabilities on the same 4-node MNIST federation
(the reference gossips raw pickled float32 over insecure channels):

- ``--mode secagg``: pairwise-masked contributions with DH key agreement
  over the gossip overlay (``learning/secagg.py``) — no individual model
  ever crosses the wire in the clear, the FedAvg aggregate is unchanged.
- ``--mode topk8``: top-k int8 delta gossip with error feedback
  (``learning/weights.py``) — ~16x smaller payloads; with ``--protocol
  grpc`` the measured weight-plane egress is printed per node.
- ``--mode int8``: dense int8 quantized gossip (4x smaller).
"""

from __future__ import annotations

import argparse


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mode", choices=["secagg", "topk8", "int8"], default="secagg")
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--rounds", type=int, default=2)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--protocol", choices=["memory", "grpc"], default="memory")
    parser.add_argument("--samples", type=int, default=4096)
    args = parser.parse_args(argv)

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    if args.mode == "secagg":
        Settings.SECURE_AGGREGATION = True  # requires the lossless wire
    else:
        Settings.WIRE_COMPRESSION = args.mode

    data = FederatedDataset.mnist(n_train=args.samples, n_test=max(args.samples // 8, 256))
    nodes = []
    for i in range(args.nodes):
        learner = JaxLearner(mlp(seed=i), data.partition(i, args.nodes), batch_size=64)
        if args.protocol == "grpc":
            from p2pfl_tpu.communication.grpc_transport import GrpcProtocol

            node = Node(learner=learner, protocol=GrpcProtocol("127.0.0.1:0"))
        else:
            node = Node(learner=learner)
        node.start()
        nodes.append(node)

    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, args.nodes - 1, only_direct=True, wait=30)

    nodes[0].set_start_learning(rounds=args.rounds, epochs=args.epochs)
    wait_to_finish(nodes, timeout=600)

    for node in nodes:
        line = f"{node.addr}: {node.learner.evaluate()}"
        stats = getattr(node.protocol, "wire_stats", None)
        if stats is not None:
            line += f"  egress: {stats['weights_bytes'] / 1e6:.2f} MB weights"
        print(line)
        node.stop()


if __name__ == "__main__":
    main()
