"""Two-process gRPC demo, process 2 of 2: connect to node1 and learn.

Reference counterpart: ``p2pfl/examples/node2.py``. Start ``node1.py``
first; this process connects over real sockets, kicks off federated
learning on both nodes, prints its result and stops.
"""

from __future__ import annotations

import argparse
import sys
import time

from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node


def main() -> None:
    parser = argparse.ArgumentParser(description="gRPC MNIST node (connects to node1)")
    parser.add_argument("port", type=int, help="node1's port")
    parser.add_argument("--rounds", type=int, default=2)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--n_train", type=int, default=2048)
    args = parser.parse_args()

    data = FederatedDataset.mnist(n_train=args.n_train, n_test=512)
    node = Node(
        learner=JaxLearner(mlp(seed=1), data.partition(1, 2), batch_size=64),
        protocol=GrpcProtocol("127.0.0.1:0"),
    )
    node.start()
    if not node.connect(f"127.0.0.1:{args.port}"):
        print("could not connect to node1 — is it running?", file=sys.stderr)
        node.stop()
        sys.exit(1)
    time.sleep(1)  # let heartbeats converge membership

    node.set_start_learning(rounds=args.rounds, epochs=args.epochs)
    while node.state.round is not None:
        time.sleep(1)

    print(f"done: {node.learner.evaluate()}", flush=True)
    node.stop()


if __name__ == "__main__":
    main()
