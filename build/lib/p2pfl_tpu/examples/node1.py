"""Two-process gRPC demo, process 1 of 2: start a node and wait.

Reference counterpart: ``p2pfl/examples/node1.py`` — one OS process per
node, meeting over real sockets. Run this first, then ``node2.py`` with the
same port:

    python -m p2pfl_tpu.examples.node1 6666
    python -m p2pfl_tpu.examples.node2 6666     # in another terminal
"""

from __future__ import annotations

import argparse

from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node


def main() -> None:
    parser = argparse.ArgumentParser(description="gRPC MNIST node (waits for node2)")
    parser.add_argument("port", type=int, help="port to listen on")
    parser.add_argument("--n_train", type=int, default=2048)
    args = parser.parse_args()

    data = FederatedDataset.mnist(n_train=args.n_train, n_test=512)
    node = Node(
        learner=JaxLearner(mlp(), data.partition(0, 2), batch_size=64),
        protocol=GrpcProtocol(f"127.0.0.1:{args.port}"),
    )
    node.start()
    print(f"node1 listening on {node.addr} — start node2 now", flush=True)
    try:
        node.protocol.wait_for_termination()
    except KeyboardInterrupt:
        node.stop()


if __name__ == "__main__":
    main()
