"""Federations that train THROUGH intra-node parallelism (MoE + GPipe).

Two runtimes from ``parallel/spmd_lm.py``:

- ``--mode moe``: N nodes federate a switch-style MoE transformer as ONE
  jitted round program on a ``(nodes, model)`` mesh — federated data
  parallelism composed with expert parallelism (expert stacks shard
  ``P(nodes, model)``; the router's balance losses ride the federated
  loss).
- ``--mode gpipe``: each node's local training runs the GPipe-pipelined
  model (microbatches stream through layer stages via ``ppermute``);
  rounds close with a host-side sample-weighted FedAvg.

Run on any multi-device backend; without hardware use the virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python -m p2pfl_tpu.examples.moe_gpipe_federation --mode moe
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mode", default="moe", choices=["moe", "gpipe"])
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--rounds", type=int, default=4)
    parser.add_argument("--expert-parallel", type=int, default=2,
                        help="model-axis width for expert sharding (moe mode)")
    parser.add_argument("--stages", type=int, default=4,
                        help="pipeline stages (gpipe mode)")
    parser.add_argument("--batch-size", type=int, default=16)
    args = parser.parse_args(argv)

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    t0 = time.monotonic()
    if args.mode == "moe":
        from p2pfl_tpu.parallel import SpmdLmFederation

        cfg = TransformerConfig(
            vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
            ffn_hidden=256, lora_rank=0, n_experts=8, moe_top_k=2,
        )
        model = tiny_transformer(seq_len=128, cfg=cfg)
        data = FederatedDataset.synthetic_lm(
            vocab_size=512, n_train=args.nodes * 256, n_test=256
        )
        fed = SpmdLmFederation.from_dataset(
            model, data, n_nodes=args.nodes, batch_size=args.batch_size,
            vote=False, expert_parallel=args.expert_parallel,
        )
        print(f"mesh: {dict(fed.mesh.shape)}")
        for _ in range(args.rounds):
            entry = fed.run_round(epochs=1)
            acc = fed.evaluate()["test_acc"]
            print(f"round {entry['round']}: loss {float(entry['train_loss']):.3f} "
                  f"next-token acc {acc:.3f}")
    else:
        from p2pfl_tpu.parallel import PipelineFederation

        cfg = TransformerConfig(
            vocab_size=512, dim=128, n_heads=8, n_kv_heads=8,
            ffn_hidden=344, lora_rank=0, n_layers=args.stages,
        )
        model = tiny_transformer(seq_len=128, cfg=cfg)
        data = FederatedDataset.synthetic_lm(
            vocab_size=512, n_train=args.nodes * 256, n_test=256
        )
        shards = [data.partition(i, args.nodes) for i in range(args.nodes)]
        fed = PipelineFederation(
            model, shards, n_stages=args.stages, batch_size=args.batch_size
        )
        for _ in range(args.rounds):
            entry = fed.run_round(epochs=1)
            acc = fed.evaluate()["test_acc"]
            print(f"round {entry['round']}: loss {entry['train_loss']:.3f} "
                  f"next-token acc {acc:.3f}")
    print(f"done in {time.monotonic() - t0:.1f}s")


if __name__ == "__main__":
    main()
