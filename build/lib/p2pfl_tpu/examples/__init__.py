"""Runnable examples, discoverable by the CLI (``p2pfl_tpu experiment list``).

Reference equivalent: ``p2pfl/examples/`` + the docstring-scraping CLI
(``p2pfl/cli.py:107-144``).
"""
