"""Non-IID federated learning: FedAvg vs FedProx vs SCAFFOLD vs FedOpt.

Dirichlet(alpha) shards give every node a skewed label distribution — the
setting where plain FedAvg drifts. This example runs the same federation
under each algorithm and prints the accuracy trajectory side by side.

The reference ships FedAvg only (``p2pfl/learning/aggregators/fedavg.py``);
its docs list Scaffold as "coming soon" (``docs/source/library_design.md``).
"""

from __future__ import annotations

import argparse
import sys


def run_one(algo: str, args) -> list[float]:
    import os

    import jax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import SpmdFederation

    # real IDX files when P2PFL_MNIST_DIR is set; otherwise the HARD
    # synthetic stand-in (multi-mode Gaussian mixture — takes ~10 rounds,
    # so algorithm differences are visible; the default one saturates in 1)
    data = FederatedDataset.mnist(
        os.environ.get("P2PFL_MNIST_DIR"), modes=8, noise=0.7, proto_scale=0.5
    )
    kwargs: dict = {}
    if algo == "fedprox":
        kwargs["prox_mu"] = args.mu
    elif algo == "scaffold":
        kwargs.update(scaffold=True, optimizer="sgd", learning_rate=args.sgd_lr)
    elif algo == "fedadam":
        kwargs.update(server_opt="adam", server_lr=args.server_lr)
    elif algo != "fedavg":
        raise ValueError(f"unknown algorithm {algo}")

    fed = SpmdFederation.from_dataset(
        mlp(),
        data,
        n_nodes=args.nodes,
        strategy="dirichlet",
        alpha=args.alpha,
        batch_size=args.batch_size,
        vote=False,
        seed=args.seed,
        **kwargs,
    )
    curve = []
    for _ in range(args.rounds):
        entry = fed.run_round(epochs=args.epochs, eval=True)
        curve.append(round(float(entry["test_acc"]), 4))
    del fed
    jax.clear_caches()
    return curve


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=5)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--alpha", type=float, default=0.3, help="Dirichlet concentration")
    parser.add_argument("--mu", type=float, default=0.1, help="FedProx proximal strength")
    parser.add_argument("--server-lr", type=float, default=0.01, help="FedOpt server lr")
    parser.add_argument("--sgd-lr", type=float, default=0.05, help="SCAFFOLD local SGD lr")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--algos", nargs="+",
        default=["fedavg", "fedprox", "scaffold", "fedadam"],
        choices=["fedavg", "fedprox", "scaffold", "fedadam"],
    )
    args = parser.parse_args(argv)

    print(f"Dirichlet({args.alpha}) x {args.nodes} nodes, {args.rounds} rounds", file=sys.stderr)
    results = {}
    for algo in args.algos:
        results[algo] = run_one(algo, args)
        print(f"{algo:>9}: {results[algo]}", flush=True)

    best = max(results, key=lambda a: results[a][-1])
    print(f"best final accuracy: {best} ({results[best][-1]})")


if __name__ == "__main__":
    main()
