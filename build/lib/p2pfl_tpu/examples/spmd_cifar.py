"""CIFAR-scale federated ResNet, SPMD mode (BASELINE configs 2/3).

ResNet-18 on CIFAR-10-shaped data (or ResNet-50 / CIFAR-100 with
``--large``), non-IID Dirichlet shards, FedAvg or robust aggregation.
Synthetic data stands in when the real datasets aren't on disk.
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=2)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--large", action="store_true", help="ResNet-50 / 100 classes")
    parser.add_argument("--aggregator", default="fedavg",
                        choices=["fedavg", "median", "trimmed_mean", "krum", "bulyan"])
    parser.add_argument("--alpha", type=float, default=0.5, help="Dirichlet concentration")
    parser.add_argument("--samples", type=int, default=16384)
    parser.add_argument("--measure_time", action="store_true")
    args = parser.parse_args(argv)

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet18, resnet50
    from p2pfl_tpu.parallel import SpmdFederation

    classes = 100 if args.large else 10
    model = (resnet50 if args.large else resnet18)(num_classes=classes)
    data = FederatedDataset.synthetic_mnist(  # CIFAR-shaped synthetic stand-in
        n_train=args.samples,
        n_test=max(args.samples // 8, 512),
        num_classes=classes,
        dim=(32, 32, 3),
    )
    fed = SpmdFederation.from_dataset(
        model,
        data,
        n_nodes=args.nodes,
        strategy="dirichlet",
        alpha=args.alpha,
        batch_size=args.batch_size,
        aggregator=args.aggregator,
        trim=max(args.nodes // 5, 1) if args.aggregator != "fedavg" else 0,
        vote=False,
    )
    t0 = time.monotonic()
    for _ in range(args.rounds):
        entry = fed.run_round(epochs=args.epochs)
        metrics = fed.evaluate()
        print(
            f"round {entry['round']}: loss={float(entry['train_loss']):.4f} "
            f"acc={metrics['test_acc']:.4f}"
        )
    if args.measure_time:
        print(f"elapsed: {time.monotonic() - t0:.2f}s ({args.nodes} nodes, {model.param_count/1e6:.1f}M params)")


if __name__ == "__main__":
    main()
