"""Heartbeat membership / failure detection.

Reference semantics (``p2pfl/communication/heartbeater.py:33-111``): a daemon
thread broadcasts a ``beat`` control message every ``HEARTBEAT_PERIOD``
seconds; every second tick it evicts neighbors whose last beat is older than
``HEARTBEAT_TIMEOUT``. Because ``beat`` TTL-floods the overlay, every node
discovers every other node as a *non-direct* neighbor within roughly one
heartbeat period (reference ``grpc_neighbors.py:34-55``).
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING

from p2pfl_tpu.settings import Settings

if TYPE_CHECKING:
    from p2pfl_tpu.communication.protocol import CommunicationProtocol

BEAT_CMD = "beat"


class Heartbeater:
    def __init__(self, self_addr: str, protocol: "CommunicationProtocol") -> None:
        self.self_addr = self_addr
        self._protocol = protocol
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeater-{self.self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def beat(self, source: str, t: float) -> None:
        """Record an incoming beat (called by the ``beat`` command handler)."""
        self._protocol.neighbors.heartbeat(source, t=None)

    def _run(self) -> None:
        tick = 0
        while not self._stop.is_set():
            msg = self._protocol.build_msg(BEAT_CMD, [str(time.time())])
            self._protocol.broadcast(msg)
            tick += 1
            if tick % 2 == 0:
                self._protocol.neighbors.evict_stale(Settings.HEARTBEAT_TIMEOUT)
            if self._stop.wait(timeout=Settings.HEARTBEAT_PERIOD):
                return
