"""Two-mode gossiper: async message plane + synchronous model-gossip loop.

Reference semantics (``p2pfl/communication/gossiper.py:31-243``):

(a) *Message plane* — a daemon thread drains a queue of
    ``(message, pending_neighbors)`` pairs, at most
    ``GOSSIP_MESSAGES_PER_PERIOD`` sends per ``GOSSIP_PERIOD``; a bounded
    ring of seen message ids provides network-wide dedup.

(b) *Model plane* — ``gossip_weights`` runs a synchronous tick loop on the
    calling (stage) thread: each tick picks ``GOSSIP_MODELS_PER_ROUND``
    random candidates, builds a per-candidate payload, sends it, and exits
    when there are no candidates, the early-stop predicate fires, or the
    observed status is unchanged for ``GOSSIP_EXIT_ON_X_EQUAL_ROUNDS`` ticks
    (convergence detector, reference 209-226).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from p2pfl_tpu.communication.message import Message
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings


class Gossiper:
    def __init__(self, self_addr: str, send_fn: Callable[..., bool]) -> None:
        self.self_addr = self_addr
        self._send = send_fn  # (nei, env, create_connection=False) -> bool
        self._queue: deque[tuple[Message, list[str]]] = deque()
        self._queue_cv = threading.Condition()
        self._processed: OrderedDict[str, None] = OrderedDict()
        self._processed_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"gossiper-{self.self_addr}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._queue_cv:
            self._queue_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # ---- dedup ring ----

    def check_and_set_processed(self, msg_id: str) -> bool:
        """True if unseen (and marks it seen); False for duplicates."""
        with self._processed_lock:
            if msg_id in self._processed:
                return False
            self._processed[msg_id] = None
            while len(self._processed) > Settings.AMOUNT_LAST_MESSAGES_SAVED:
                self._processed.popitem(last=False)
            return True

    # ---- message plane ----

    def add_message(self, msg: Message, pending_neis: list[str]) -> None:
        if not pending_neis:
            return
        with self._queue_cv:
            self._queue.append((msg, list(pending_neis)))
            self._queue_cv.notify()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._queue_cv:
                if not self._queue:
                    self._queue_cv.wait(timeout=Settings.GOSSIP_PERIOD)
                    continue
                batch: list[tuple[Message, str]] = []
                budget = Settings.GOSSIP_MESSAGES_PER_PERIOD
                while self._queue and budget > 0:
                    msg, neis = self._queue.popleft()
                    take, rest = neis[:budget], neis[budget:]
                    batch.extend((msg, n) for n in take)
                    budget -= len(take)
                    if rest:
                        self._queue.appendleft((msg, rest))
                        break
            for msg, nei in batch:
                if self._stop.is_set():
                    return
                self._send(nei, msg)
            time.sleep(Settings.GOSSIP_PERIOD)

    # ---- model plane ----

    def gossip_weights(
        self,
        early_stopping_fn: Callable[[], bool],
        get_candidates_fn: Callable[[], list[str]],
        status_fn: Callable[[], object],
        model_fn: Callable[[str], Optional[object]],
        period: Optional[float] = None,
        create_connection: bool = False,
    ) -> None:
        from p2pfl_tpu.communication.protocol import random_subset

        period = Settings.GOSSIP_MODELS_PERIOD if period is None else period
        last_status: object = None
        equal_ticks = 0
        while True:
            if early_stopping_fn() or self._stop.is_set():
                return
            candidates = get_candidates_fn()
            if not candidates:
                return
            status = status_fn()
            if status == last_status:
                equal_ticks += 1
                if equal_ticks >= Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS:
                    logger.debug(
                        self.self_addr,
                        f"Gossip stalled for {equal_ticks} ticks — stopping (status={status})",
                    )
                    return
            else:
                equal_ticks = 0
                last_status = status
            for nei in random_subset(candidates, Settings.GOSSIP_MODELS_PER_ROUND):
                payload = model_fn(nei)
                if payload is None:
                    continue
                self._send(nei, payload, create_connection=create_connection)
            time.sleep(period)
