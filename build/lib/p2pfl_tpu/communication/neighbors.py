"""Thread-safe neighbor registry.

Semantics from the reference's ``p2pfl/communication/neighbors.py:27-170``:
a map addr → :class:`NeighborInfo`; *direct* neighbors were connected
explicitly (transport connection + handshake), *non-direct* neighbors are
learned from TTL-flooded heartbeats and can only be reached by creating an
ad-hoc connection.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from p2pfl_tpu.management.logger import logger


@dataclass
class NeighborInfo:
    direct: bool
    conn: Any = None  # transport-specific handle (channel/stub/server ref)
    last_beat: float = field(default_factory=time.monotonic)


class Neighbors:
    """Base neighbors manager. Transports override the connect/disconnect hooks."""

    def __init__(self, self_addr: str) -> None:
        self.self_addr = self_addr
        self._lock = threading.Lock()
        self._neis: dict[str, NeighborInfo] = {}

    # ---- transport hooks ----

    def _connect(self, addr: str, handshake: bool) -> Optional[Any]:
        """Open a transport connection; return the handle or raise. Base: none."""
        return None

    def _disconnect(self, addr: str, conn: Any, notify: bool) -> None:
        """Close a transport connection (best-effort)."""

    # ---- registry ----

    def add(self, addr: str, non_direct: bool = False, handshake: bool = True) -> bool:
        """Register a neighbor. Direct adds open a connection + handshake.

        Re-adding an already-direct neighbor is a no-op; a heartbeat from a
        direct neighbor must NOT demote it to non-direct
        (reference ``neighbors.py:73-110``).
        """
        if addr == self.self_addr:
            return False
        with self._lock:
            existing = self._neis.get(addr)
            if existing is not None:
                if non_direct:
                    existing.last_beat = time.monotonic()
                    return True
                if existing.direct:
                    logger.debug(self.self_addr, f"Already connected to {addr}")
                    return False
                # upgrade non-direct → direct below (outside dict mutation)
        if non_direct:
            with self._lock:
                if addr not in self._neis:
                    self._neis[addr] = NeighborInfo(direct=False)
            return True
        try:
            conn = self._connect(addr, handshake)
        except Exception as exc:  # noqa: BLE001 — connection errors are expected
            logger.info(self.self_addr, f"Cannot connect to {addr}: {exc}")
            return False
        with self._lock:
            self._neis[addr] = NeighborInfo(direct=True, conn=conn)
        return True

    def remove(self, addr: str, disconnect_msg: bool = False) -> None:
        with self._lock:
            info = self._neis.pop(addr, None)
        if info is not None and info.direct:
            try:
                self._disconnect(addr, info.conn, notify=disconnect_msg)
            except Exception:  # noqa: BLE001
                pass

    def heartbeat(self, addr: str, t: Optional[float] = None) -> None:
        """Record a beat; unknown senders become non-direct neighbors."""
        with self._lock:
            info = self._neis.get(addr)
            if info is None:
                if addr != self.self_addr:
                    self._neis[addr] = NeighborInfo(direct=False)
                return
            info.last_beat = time.monotonic() if t is None else t

    def evict_stale(self, timeout: float) -> list[str]:
        """Drop neighbors whose last beat is older than ``timeout`` seconds."""
        now = time.monotonic()
        with self._lock:
            stale = [a for a, i in self._neis.items() if now - i.last_beat > timeout]
        for addr in stale:
            logger.info(self.self_addr, f"Heartbeat timeout — evicting {addr}")
            self.remove(addr)
        return stale

    def get(self, addr: str) -> Optional[NeighborInfo]:
        with self._lock:
            return self._neis.get(addr)

    def get_all(self, only_direct: bool = False) -> dict[str, NeighborInfo]:
        with self._lock:
            if only_direct:
                return {a: i for a, i in self._neis.items() if i.direct}
            return dict(self._neis)

    def clear(self, disconnect: bool = False) -> None:
        for addr in list(self.get_all(only_direct=True)):
            self.remove(addr, disconnect_msg=disconnect)
        with self._lock:
            self._neis.clear()
