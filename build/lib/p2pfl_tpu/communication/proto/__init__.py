"""Wire schemas: the documented envelope format (node.proto) and the
reference-compatible protobuf interop schema (interop.proto + generated
interop_pb2). See communication/proto_wire.py for scope and the
no-pickle divergence."""
