"""Communication layer: protocol seam, membership, gossip, transports.

Mirrors the layering of the reference's ``p2pfl/communication/`` (SURVEY §2.4):
a transport-agnostic :class:`~p2pfl_tpu.communication.protocol.CommunicationProtocol`
seam with interchangeable stacks — in-memory (simulation), TCP/gRPC (real
network), and the TPU-native mesh-collective runtime in
``p2pfl_tpu.parallel`` that replaces per-message transport entirely.
"""

from p2pfl_tpu.communication.message import Message, WeightsEnvelope
from p2pfl_tpu.communication.protocol import CommunicationProtocol

__all__ = ["CommunicationProtocol", "Message", "WeightsEnvelope"]
