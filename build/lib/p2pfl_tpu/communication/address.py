"""Address parsing for network transports.

Reference: ``p2pfl/communication/grpc/address.py`` — IPv4, IPv6 and unix
sockets, with an OS-assigned free port when none is given (:60-63). gRPC
target strings: ``host:port``, ``[v6::addr]:port``, ``unix:/path.sock``.
"""

from __future__ import annotations

import re
import socket
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Address:
    target: str  # the canonical gRPC target string
    kind: str  # "ipv4" | "ipv6" | "unix"
    host: Optional[str] = None
    port: Optional[int] = None


_V6 = re.compile(r"^\[(?P<host>[0-9a-fA-F:]+)\](?::(?P<port>\d+))?$")
_V4 = re.compile(r"^(?P<host>[^:\[\]]+)(?::(?P<port>\d+))?$")


def parse_address(addr: Optional[str] = None) -> Address:
    """Normalize an address, assigning a free port where needed."""
    if addr is None or addr == "":
        addr = "127.0.0.1:0"
    if addr.startswith("unix:"):
        return Address(addr, "unix")
    m = _V6.match(addr)
    if m:
        host = m.group("host")
        port = int(m.group("port") or 0) or free_port(host, socket.AF_INET6)
        return Address(f"[{host}]:{port}", "ipv6", host, port)
    m = _V4.match(addr)
    if m:
        host = m.group("host")
        port = int(m.group("port") or 0) or free_port(host)
        return Address(f"{host}:{port}", "ipv4", host, port)
    raise ValueError(f"unparseable address {addr!r}")


def free_port(host: str = "127.0.0.1", family: int = socket.AF_INET) -> int:
    """OS-assigned free port (reference ``address.py:60-63``)."""
    with socket.socket(family, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]
