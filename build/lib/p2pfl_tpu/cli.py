"""Command-line interface.

Reference: Typer app with ``experiment list`` / ``experiment run``
(``p2pfl/cli.py:65-203``). argparse here (typer isn't in this image);
same surface: examples are discovered from ``p2pfl_tpu/examples/`` and run
in-process with their own argv.
"""

from __future__ import annotations

import argparse
import importlib
import pkgutil
import sys


def _discover() -> dict[str, str]:
    """Example name → first docstring line."""
    import p2pfl_tpu.examples as ex

    out = {}
    for info in pkgutil.iter_modules(ex.__path__):
        mod = importlib.import_module(f"p2pfl_tpu.examples.{info.name}")
        doc = (mod.__doc__ or "").strip().splitlines()
        out[info.name] = doc[0] if doc else ""
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="p2pfl_tpu", description="TPU-native federated learning")
    sub = parser.add_subparsers(dest="command")

    exp = sub.add_parser("experiment", help="list or run bundled experiments")
    exp_sub = exp.add_subparsers(dest="action")
    exp_sub.add_parser("list", help="list available experiments")
    run = exp_sub.add_parser("run", help="run an experiment by name")
    run.add_argument("name")
    run.add_argument("extra", nargs=argparse.REMAINDER, help="arguments passed to the experiment")

    sub.add_parser("bench", help="run the headline benchmark")
    # remote-management verbs are stubs in the reference too (cli.py:71-95)
    for stub in ("login", "remote", "launch"):
        sub.add_parser(stub, help="(coming soon)")

    args = parser.parse_args(argv)
    if args.command in ("login", "remote", "launch"):
        print(f"{args.command}: coming soon (stub — reference parity, cli.py:71-95)")
        return 0
    if args.command == "experiment":
        if args.action == "list":
            for name, doc in sorted(_discover().items()):
                print(f"{name:20s} {doc}")
            return 0
        if args.action == "run":
            examples = _discover()
            if args.name not in examples:
                print(f"unknown experiment {args.name!r}; try: {', '.join(sorted(examples))}")
                return 1
            mod = importlib.import_module(f"p2pfl_tpu.examples.{args.name}")
            mod.main(args.extra)
            return 0
        exp.print_help()
        return 1
    if args.command == "bench":
        import runpy

        runpy.run_path("bench.py", run_name="__main__")
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
