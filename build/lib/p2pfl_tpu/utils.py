"""Simulation / test harness helpers (reference ``p2pfl/utils.py:37-138``).

Shipped in the package (not test-only), matching the reference: these are the
supported way for users to script multi-node experiments.
"""

from __future__ import annotations

import time
from typing import Iterable

import jax
import numpy as np

from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import set_test_settings  # noqa: F401 — re-export (reference parity)


def wait_convergence(
    nodes: Iterable[Node], n_neis: int, only_direct: bool = False, wait: float = 5.0
) -> None:
    """Block until every node sees ``n_neis`` neighbors (or raise)."""
    deadline = time.monotonic() + wait
    nodes = list(nodes)
    while time.monotonic() < deadline:
        if all(len(n.get_neighbors(only_direct=only_direct)) == n_neis for n in nodes):
            return
        time.sleep(0.05)
    counts = {n.addr: len(n.get_neighbors(only_direct=only_direct)) for n in nodes}
    raise AssertionError(f"Convergence not reached: {counts} (wanted {n_neis})")


def full_connection(node: Node, nodes: Iterable[Node]) -> None:
    """Directly connect ``node`` to every node in ``nodes``."""
    for other in nodes:
        if other.addr != node.addr:
            node.connect(other.addr)


def connect_line(nodes: list[Node]) -> None:
    """Line topology: node[i] → node[i+1] (the reference example's shape)."""
    for a, b in zip(nodes, nodes[1:]):
        a.connect(b.addr)


def wait_to_finish(nodes: Iterable[Node], timeout: float = 120.0, min_experiments: int = 1) -> None:
    """Poll until every node has run ``min_experiments`` and is idle again.

    Reference ``wait_4_results`` polls ``round is None`` only — which is
    also true *before* learning threads start, a race this version closes
    via ``NodeState.experiment_epoch``.
    """
    deadline = time.monotonic() + timeout
    nodes = list(nodes)
    while time.monotonic() < deadline:
        if all(
            n.state.experiment_epoch >= min_experiments and n.state.round is None for n in nodes
        ):
            return
        time.sleep(0.1)
    status = {n.addr: (n.state.experiment_epoch, n.state.round) for n in nodes}
    raise AssertionError(f"Nodes did not finish in {timeout}s: (epoch, round)={status}")


# reference-parity alias
wait_4_results = wait_to_finish


def check_equal_models(nodes: Iterable[Node], atol: float = 1e-1) -> None:
    """Assert all nodes hold (approximately) the same parameters.

    Reference: np.allclose with atol=1e-1 (``utils.py:112-138``) — loose
    because nodes keep training between aggregation and comparison.
    """
    params = [n.learner.get_parameters() for n in nodes]
    first_leaves = jax.tree.leaves(params[0])
    for other in params[1:]:
        other_leaves = jax.tree.leaves(other)
        assert len(first_leaves) == len(other_leaves), "different model structures"
        for a, b in zip(first_leaves, other_leaves):
            np.testing.assert_allclose(
                np.asarray(a, dtype=np.float32),
                np.asarray(b, dtype=np.float32),
                atol=atol,
            )
