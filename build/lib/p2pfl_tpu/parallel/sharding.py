"""Parameter sharding rules: tensor parallelism over the ``model`` mesh axis.

For models too big (or too slow) for one chip, transformer weights shard
over ``Settings.MESH_MODEL_AXIS`` following the Megatron pattern:

- attention q/k/v projections: column-parallel (shard the head/output dim),
- attention output projection: row-parallel (shard the input dim),
- MLP gate/up (w1/w3): column-parallel; down (w2): row-parallel,
- embeddings: shard the vocab dim; norms and LoRA adapters replicate
  (adapters are tiny and are the federated payload — keeping them
  replicated makes the FedAvg collective mesh-local).

XLA inserts the matching all-reduces at the row-parallel boundaries; with
sequence sharded on the same axis (ring attention) activations stay
distributed end to end.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from p2pfl_tpu.settings import Settings

Pytree = Any

# (path regex, spec builder) — first match wins; paths look like
# "layer_0/attn/wq/kernel". LoRA params replicate (they're the federated unit).
_RULES: list[tuple[str, tuple]] = [
    (r"lora_", ()),  # replicated
    (r"attn/(wq|wk|wv)/kernel", (None, "model")),  # column-parallel
    (r"attn/wo/kernel", ("model", None)),  # row-parallel
    (r"mlp/(w1|w3)/kernel", (None, "model")),  # column-parallel
    (r"mlp/w2/kernel", ("model", None)),  # row-parallel
    # expert parallelism: MoE expert stacks [E, ...] shard the expert axis;
    # XLA turns the dispatch/combine einsums into token all-to-alls.
    # Router replicates (every chip routes its own tokens).
    (r"mlp/router$", ()),
    (r"mlp/w[123]$", ("model", None, None)),
    (r"embed", ("model", None)),  # vocab-sharded embeddings
]


def partition_spec_for(path: str) -> P:
    for pattern, axes in _RULES:
        if re.search(pattern, path):
            named = tuple(
                Settings.MESH_MODEL_AXIS if a == "model" else a for a in axes
            )
            return P(*named)
    return P()  # replicate (norm scales, biases)


def _path_str(key_path) -> str:
    parts = []
    for p in key_path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))))
    return "/".join(parts)


def transformer_shardings(mesh: Mesh, params: Pytree) -> Pytree:
    """NamedSharding pytree for a transformer param tree on ``mesh``."""

    def one(key_path, leaf):
        spec = partition_spec_for(_path_str(key_path))
        # drop axis specs that don't divide the dim (tiny configs on big meshes)
        fixed = []
        for i, axis in enumerate(spec):
            if axis is None:
                fixed.append(None)
                continue
            size = mesh.shape[axis]
            if i < leaf.ndim and leaf.shape[i] % size == 0:
                fixed.append(axis)
            else:
                fixed.append(None)
        return NamedSharding(mesh, P(*fixed))

    return jax.tree_util.tree_map_with_path(one, params)


def shard_transformer(mesh: Mesh, params: Pytree) -> Pytree:
    """Place a transformer param tree onto the mesh per the TP rules."""
    return jax.device_put(params, transformer_shardings(mesh, params))
