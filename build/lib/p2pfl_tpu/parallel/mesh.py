"""Mesh construction helpers.

Axis convention (Settings.MESH_NODES_AXIS / MESH_MODEL_AXIS):
- ``nodes``: one federated node per slot — data-parallel across the
  federation; collectives over this axis ride ICI within a slice.
- ``model``: intra-node model sharding (tensor/sequence parallel) for
  models too big for one chip (BASELINE config 5). Size 1 by default.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from p2pfl_tpu.settings import Settings


def federation_mesh(
    n_nodes: Optional[int] = None,
    model_parallel: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(nodes, model)`` mesh from the available devices.

    ``n_nodes`` is the number of mesh slots along the nodes axis — logical
    federated nodes are folded onto slots (multiple nodes per slot when the
    federation is larger than the device count). Defaults to
    ``len(devices) // model_parallel``.
    """
    devices = list(devices if devices is not None else jax.devices())
    if model_parallel < 1 or len(devices) % model_parallel != 0:
        raise ValueError(f"model_parallel={model_parallel} does not divide {len(devices)} devices")
    slots = len(devices) // model_parallel
    if n_nodes is not None:
        slots = min(slots, n_nodes)
        # keep the mesh rectangular: use the largest slot count that divides evenly
        while len(devices) % (slots * model_parallel) != 0:
            slots -= 1
    use = devices[: slots * model_parallel]
    arr = np.array(use).reshape(slots, model_parallel)
    return Mesh(arr, (Settings.MESH_NODES_AXIS, Settings.MESH_MODEL_AXIS))
