"""p2pfl_tpu — a TPU-native decentralized federated learning framework.

A brand-new implementation of the capabilities of the reference framework
(Angel3245/p2pfl, see /root/reference): peer-to-peer federated learning with
train-set election by voting, local training, gossip-based FedAvg aggregation,
heartbeat membership, and pluggable transports — redesigned JAX-first:

- model weights are ``jax.Array`` pytrees, aggregation is a jitted
  ``tree_map`` (reference: python loop over state dicts,
  ``p2pfl/learning/aggregators/fedavg.py:43-60``),
- each logical node's trainer is a jit-compiled train step
  (reference: PyTorch Lightning ``Trainer`` per round,
  ``p2pfl/learning/pytorch/lightning_learner.py:180-198``),
- a whole federation can run as ONE SPMD program over a
  ``jax.sharding.Mesh`` (one node per chip / per mesh slot), with model
  exchange as masked collectives over ICI instead of gRPC.

The transport seam (``CommunicationProtocol``) is preserved, so in-memory
simulation, gRPC real-network mode, and the mesh-collective mode are
interchangeable per node — mirroring the reference seam at
``p2pfl/communication/communication_protocol.py:27-190``.
"""

__version__ = "0.1.0"

from p2pfl_tpu.settings import Settings

__all__ = ["Node", "Settings", "__version__"]


def __getattr__(name):
    # lazy: importing the package must not pull the full comm stack
    if name == "Node":
        from p2pfl_tpu.node import Node

        return Node
    raise AttributeError(name)
