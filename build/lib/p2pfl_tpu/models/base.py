"""Model wrapper: a flax module + its parameters + metadata.

The reference couples model code to PyTorch Lightning modules; here a model
is (pure apply function, params pytree). Two instances of the same
architecture share one jit cache entry because linen modules are frozen
dataclasses with structural equality — N simulated nodes compile each step
exactly once.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp

Pytree = Any


def apply_with_aux(module: Any, params: Pytree, x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Apply + total auxiliary loss sown into the ``"moe_losses"`` collection.

    MoE layers sow their load-balance/z-loss scalars there
    (``models/transformer.py:MoEMLP``); training losses must include the
    sum or the router never learns to balance. For models without sown
    losses the collection is empty and the aux term is 0 — the extra
    ``mutable`` plumbing is free under jit.
    """
    out, mut = module.apply({"params": params}, x, mutable=["moe_losses"])
    leaves = jax.tree.leaves(mut)
    aux = sum(leaves) if leaves else jnp.zeros((), jnp.float32)
    return out, aux


@dataclass
class FlaxModel:
    """A flax module bound to a concrete parameter pytree."""

    module: Any  # flax.linen.Module
    params: Pytree
    input_shape: tuple[int, ...]  # per-example shape, no batch dim
    num_classes: int = 10
    extra: dict = field(default_factory=dict)

    @classmethod
    def create(
        cls,
        module: Any,
        input_shape: tuple[int, ...],
        seed: int = 0,
        num_classes: int = 10,
    ) -> "FlaxModel":
        rng = jax.random.PRNGKey(seed)
        dummy = jnp.zeros((1, *input_shape), dtype=jnp.float32)
        variables = module.init(rng, dummy)
        return cls(module, variables["params"], input_shape, num_classes)

    def apply(self, params: Pytree, x: jax.Array) -> jax.Array:
        return self.module.apply({"params": params}, x)

    def apply_with_aux(self, params: Pytree, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        return apply_with_aux(self.module, params, x)

    @property
    def param_count(self) -> int:
        return sum(int(p.size) for p in jax.tree.leaves(self.params))
