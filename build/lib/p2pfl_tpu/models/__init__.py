"""Model zoo: flax modules wrapped for federated use.

Reference models: MLP 784-256-128-10 (``mnist_examples/models/mlp.py:53-56``)
and a 2-conv CNN (``models/cnn.py:55-71``). Added for the BASELINE configs:
ResNet-18/50 (CIFAR) and a LoRA transformer (federated fine-tune).
"""

from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.models.vision import CNN, MLP, ResNet, ViT, cnn, mlp, resnet18, resnet50, vit

__all__ = [
    "FlaxModel", "MLP", "CNN", "ResNet", "ViT",
    "mlp", "cnn", "resnet18", "resnet50", "vit",
]
