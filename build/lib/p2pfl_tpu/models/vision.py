"""Vision models: MLP, CNN (reference parity) and ResNet-18/50 (BASELINE).

Compute runs in bfloat16 (MXU-friendly), parameters and logits stay float32
— the standard TPU mixed-precision recipe. Reference shapes:
MLP 784-256-128-10 (``mlp.py:53-56``), 2-conv CNN (``cnn.py:55-71``).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from p2pfl_tpu.models.base import FlaxModel


class MLP(nn.Module):
    """784-256-128-10 MLP, the reference's default MNIST model."""

    hidden: Sequence[int] = (256, 128)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class CNN(nn.Module):
    """Two-conv CNN over 28x28x1, matching the reference CNN's capability."""

    channels: Sequence[int] = (32, 64)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class ResBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), self.strides, use_bias=False, dtype=self.dtype
            )(residual)
            residual = nn.GroupNorm(num_groups=8, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), self.strides, use_bias=False, dtype=self.dtype
            )(residual)
            residual = nn.GroupNorm(num_groups=8, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet for CIFAR-scale inputs.

    GroupNorm instead of BatchNorm: federated averaging of BatchNorm running
    statistics is ill-defined across non-IID shards (a known FL failure
    mode); GroupNorm keeps every parameter a plain weight that FedAvg can
    average soundly — and avoids mutable state in the train step.
    """

    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    bottleneck: bool = False
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        x = nn.relu(x)
        block = BottleneckBlock if self.bottleneck else ResBlock
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(64 * 2**i, strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


# ---- constructors (bound to concrete params) ----


def mlp(seed: int = 0, num_classes: int = 10, input_shape=(28, 28, 1)) -> FlaxModel:
    return FlaxModel.create(MLP(num_classes=num_classes), input_shape, seed, num_classes)


def cnn(seed: int = 0, num_classes: int = 10, input_shape=(28, 28, 1)) -> FlaxModel:
    return FlaxModel.create(CNN(num_classes=num_classes), input_shape, seed, num_classes)


def resnet18(seed: int = 0, num_classes: int = 10, input_shape=(32, 32, 3)) -> FlaxModel:
    return FlaxModel.create(
        ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes), input_shape, seed, num_classes
    )


def resnet50(seed: int = 0, num_classes: int = 100, input_shape=(32, 32, 3)) -> FlaxModel:
    return FlaxModel.create(
        ResNet(stage_sizes=(3, 4, 6, 3), bottleneck=True, num_classes=num_classes),
        input_shape,
        seed,
        num_classes,
    )


class ViTBlock(nn.Module):
    """Pre-norm encoder block: bidirectional MHA + GELU MLP (ViT recipe).
    Width is derived from the input's last dim."""

    heads: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # [B, T, D]
        b, t, d = x.shape
        h = self.heads
        hd = d // h
        y = nn.LayerNorm(dtype=jnp.float32)(x).astype(self.dtype)
        qkv = nn.Dense(3 * d, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv.reshape(b, t, 3, h, hd), 3, axis=2)
        q, k, v = (a.squeeze(2) for a in (q, k, v))  # [B, T, H, hd]
        # bidirectional attention, fp32 softmax statistics
        s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32), k.astype(jnp.float32))
        a = jax.nn.softmax(s * hd**-0.5, axis=-1).astype(self.dtype)
        o = jnp.einsum("bhts,bshd->bthd", a, v).reshape(b, t, d)
        x = x + nn.Dense(d, dtype=self.dtype, name="proj")(o)
        y = nn.LayerNorm(dtype=jnp.float32)(x).astype(self.dtype)
        y = nn.Dense(self.mlp_ratio * d, dtype=self.dtype, name="fc1")(y)
        y = nn.Dense(d, dtype=self.dtype, name="fc2")(nn.gelu(y))
        return x + y


class ViT(nn.Module):
    """Small vision transformer (Dosovitskiy et al. 2020): conv patch embed,
    learned position embeddings, mean-pooled head. Fills the attention-based
    vision slot of the model zoo (the reference has only MLP/CNN,
    ``mnist_examples/models/``)."""

    num_classes: int = 10
    patch: int = 4
    dim: int = 64
    depth: int = 4
    heads: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # [B, H, W, C]
        x = nn.Conv(
            self.dim, (self.patch, self.patch), strides=(self.patch, self.patch),
            dtype=self.dtype, name="patch_embed",
        )(x.astype(self.dtype))
        b, hh, ww, d = x.shape
        x = x.reshape(b, hh * ww, d)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, hh * ww, d)
        )
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = ViTBlock(self.heads, dtype=self.dtype, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32)(x.mean(axis=1))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def vit(
    seed: int = 0,
    num_classes: int = 10,
    input_shape=(32, 32, 3),
    patch: int = 4,
    dim: int = 64,
    depth: int = 4,
    heads: int = 4,
    dtype: jnp.dtype = jnp.bfloat16,
) -> FlaxModel:
    """``dtype=jnp.float32`` for CPU runs — bf16 is software-emulated there
    (the default bf16 is the TPU/MXU recipe)."""
    return FlaxModel.create(
        ViT(
            num_classes=num_classes, patch=patch, dim=dim, depth=depth,
            heads=heads, dtype=dtype,
        ),
        input_shape,
        seed=seed,
        num_classes=num_classes,
    )
