"""Per-node resource telemetry.

Reference: ``p2pfl/management/node_monitor.py:31-86`` — a daemon thread
sampling CPU% / RAM% / network MB/s every ``RESOURCE_MONITOR_PERIOD``.
Added here: per-device TPU/accelerator memory stats via
``jax.local_devices()[i].memory_stats()`` where the backend exposes them —
the number that actually matters on a chip.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.settings import Settings

ReportFn = Callable[[str, str, float], None]  # (node, metric, value)


def _default_report(node: str, metric: str, value: float) -> None:
    logger.log_metric(node, metric, value, step=int(time.time()))


class NodeMonitor:
    def __init__(self, node: str, report_fn: Optional[ReportFn] = None) -> None:
        self.node = node
        self._report = report_fn or _default_report
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_net: Optional[tuple[float, float, float]] = None

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name=f"monitor-{self.node}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        try:
            import psutil
        except ImportError:  # psutil is present in this image, but stay robust
            logger.debug(self.node, "psutil unavailable — resource monitor disabled")
            return
        while not self._stop.is_set():
            try:
                self._report(self.node, "cpu_percent", psutil.cpu_percent(interval=None))
                self._report(self.node, "ram_percent", psutil.virtual_memory().percent)
                net = psutil.net_io_counters()
                now = time.monotonic()
                if self._last_net is not None:
                    t0, sent0, recv0 = self._last_net
                    dt = max(now - t0, 1e-6)
                    self._report(self.node, "net_out_mbs", (net.bytes_sent - sent0) / dt / 1e6)
                    self._report(self.node, "net_in_mbs", (net.bytes_recv - recv0) / dt / 1e6)
                self._last_net = (now, net.bytes_sent, net.bytes_recv)
                self._report_device_memory()
            except Exception as exc:  # noqa: BLE001 — telemetry must never kill a node
                logger.debug(self.node, f"monitor sample failed: {exc}")
            if self._stop.wait(timeout=Settings.RESOURCE_MONITOR_PERIOD):
                return

    def _report_device_memory(self) -> None:
        import jax

        for i, dev in enumerate(jax.local_devices()):
            stats = getattr(dev, "memory_stats", lambda: None)()
            if stats and "bytes_in_use" in stats:
                self._report(self.node, f"device{i}_mem_mb", stats["bytes_in_use"] / 1e6)
