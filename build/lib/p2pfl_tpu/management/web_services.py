"""REST client for a p2pfl-web-style dashboard.

Reference: ``p2pfl/management/p2pfl_web_services.py:58-269`` — five endpoints
authenticated by an ``x-api-key`` header. stdlib-only (urllib); failures are
logged and swallowed so a dead dashboard can never take down training
(same policy as the reference).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from typing import Optional

from p2pfl_tpu.management.logger import logger


class WebServices:
    def __init__(self, url: str, api_key: str, timeout: float = 5.0) -> None:
        self.url = url.rstrip("/")
        self.api_key = api_key
        self.timeout = timeout
        self._node_key: Optional[str] = None
        self._lock = threading.Lock()

    # ---- endpoints (reference line refs: 82 / 116 / 153 / 194 / 233) ----

    def register_node(self, node: str, is_simulated: bool = False) -> None:
        resp = self._post("/node", {"address": node, "is_simulated": is_simulated})
        if resp is not None:
            with self._lock:
                self._node_key = resp.get("node_key")

    def unregister_node(self, node: str) -> None:
        self._post("/node-stop", {"address": node})

    def send_log(self, time: str, node: str, level: str, message: str) -> None:
        self._post("/node-log", {"time": time, "address": node, "level": level, "message": message})

    def send_local_metric(self, exp: str, round: int, metric: str, node: str, step: int, value: float) -> None:  # noqa: A002
        self._post(
            "/node-metric/local",
            {"experiment": exp, "round": round, "metric": metric, "address": node, "step": step, "value": value},
        )

    def send_global_metric(self, exp: str, round: int, metric: str, node: str, value: float) -> None:  # noqa: A002
        self._post(
            "/node-metric/global",
            {"experiment": exp, "round": round, "metric": metric, "address": node, "value": value},
        )

    def send_system_metric(self, node: str, metric: str, value: float, time: str) -> None:
        self._post("/node-metric/system", {"address": node, "metric": metric, "value": value, "time": time})

    # ---- plumbing ----

    def _post(self, path: str, payload: dict) -> Optional[dict]:
        req = urllib.request.Request(
            self.url + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", "x-api-key": self.api_key},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = resp.read().decode() or "{}"
                return json.loads(body)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            logger.debug("web-services", f"POST {path} failed: {exc}")
            return None
