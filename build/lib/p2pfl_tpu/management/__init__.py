"""Management / observability: logger facade, metric storage, node monitor."""
