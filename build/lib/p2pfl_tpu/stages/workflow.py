"""Workflow loop (reference ``p2pfl/stages/workflows.py:28-47``)."""

from __future__ import annotations

from typing import TYPE_CHECKING

from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


class LearningWorkflow:
    """Runs stages until one returns ``None``. Exceptions end the experiment."""

    def run(self, node: "Node") -> None:
        import time

        from p2pfl_tpu.stages.learning_stages import StartLearningStage

        stage = StartLearningStage
        while stage is not None:
            logger.debug(node.addr, f"── stage: {stage.name}")
            # stall-watchdog instrumentation (management/watchdog.py)
            node.state.current_stage = stage.name
            node.state.last_transition = time.monotonic()
            try:
                stage = stage.execute(node)
            except Exception as exc:  # noqa: BLE001 — stage failure ends learning, not the node
                if node.learning_interrupted():
                    logger.info(node.addr, f"Learning interrupted during {stage.name}")
                else:
                    logger.error(node.addr, f"Stage {stage.name} failed: {exc!r}")
                return
