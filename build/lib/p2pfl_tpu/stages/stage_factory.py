"""String-keyed stage registry (reference ``stages/stage_factory.py:26-59``).

The reference uses lazy imports here to break circular dependencies; this
rebuild's stages don't import the factory, so a plain registry suffices.
Custom workflows can register their own stages and jump into the FSM.
"""

from __future__ import annotations

from typing import Type

from p2pfl_tpu.stages.stage import Stage


class StageFactory:
    _registry: dict[str, Type[Stage]] = {}

    @classmethod
    def register(cls, stage: Type[Stage]) -> Type[Stage]:
        cls._registry[stage.name] = stage
        return stage

    @classmethod
    def get_stage(cls, name: str) -> Type[Stage]:
        cls._ensure_builtins()
        if name not in cls._registry:
            raise KeyError(f"unknown stage {name!r}; known: {sorted(cls._registry)}")
        return cls._registry[name]

    @classmethod
    def _ensure_builtins(cls) -> None:
        if cls._registry:
            return
        from p2pfl_tpu.stages import learning_stages as ls

        for stage in (
            ls.StartLearningStage,
            ls.VoteTrainSetStage,
            ls.TrainStage,
            ls.WaitAggregatedModelsStage,
            ls.GossipModelStage,
            ls.RoundFinishedStage,
        ):
            cls._registry[stage.name] = stage
