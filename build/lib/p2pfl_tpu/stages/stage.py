"""Stage ABC (reference ``p2pfl/stages/stage.py:23-34``)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import TYPE_CHECKING, Optional, Type

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


class Stage(ABC):
    """One state of the round FSM. ``execute`` returns the next stage class."""

    name = "Stage"

    @staticmethod
    @abstractmethod
    def execute(node: "Node") -> Optional[Type["Stage"]]:
        ...
