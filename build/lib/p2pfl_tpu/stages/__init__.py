"""Round-workflow FSM (SURVEY §2.2).

A learning experiment is a finite-state machine driven on the node's
learning thread: each stage does host-side coordination (votes, gossip,
waiting on events) and invokes device work (train/eval/aggregate) as pure
jitted functions between states — all blocking stays on host, per the
build-plan note on blocking control flow vs JAX (SURVEY §7).
"""

from p2pfl_tpu.stages.stage import Stage
from p2pfl_tpu.stages.workflow import LearningWorkflow

__all__ = ["Stage", "LearningWorkflow"]
