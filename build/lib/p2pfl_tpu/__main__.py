"""``python -m p2pfl_tpu`` entry point (reference ``p2pfl/__main__.py``)."""

import sys

from p2pfl_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
