"""``beat`` command (reference ``p2pfl/commands/heartbeat_command.py:70``)."""

from __future__ import annotations

from p2pfl_tpu.commands.command import Command


class HeartbeatCommand(Command):
    def __init__(self, heartbeater) -> None:
        self._heartbeater = heartbeater

    @staticmethod
    def get_name() -> str:
        return "beat"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        t = float(args[0]) if args else 0.0
        self._heartbeater.beat(source, t)
