"""Command ABC (reference ``p2pfl/commands/command.py:24-43``)."""

from __future__ import annotations

from abc import ABC, abstractmethod


class Command(ABC):
    @staticmethod
    @abstractmethod
    def get_name() -> str:
        ...

    @abstractmethod
    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        ...
