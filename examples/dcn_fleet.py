"""Multi-process DCN fleet driver: real federation over the DCN weights plane.

Spawns N OS processes that form ONE ``jax.distributed`` world (CPU: gloo
collectives, wired by ``init_multihost``; on a TPU pod the same code rides
the real DCN), runs M gRPC nodes per process through a full federated
experiment, and reports where the model payloads actually travelled:

- co-resident node pairs ride the ICI plane (device-to-device, one process),
- cross-process same-world pairs ride the DCN plane (XLA cross-host
  collectives — ZERO pickled weight bytes on gRPC between them),
- anything else falls back to the byte path, loudly and per edge.

Modes:

    python examples/dcn_fleet.py                    # 2 procs × 1 node, 2 rounds
    python examples/dcn_fleet.py --procs 3 --nodes-per-proc 2 --rounds 3
    python examples/dcn_fleet.py --plane bytes      # control run, byte transport
    python examples/dcn_fleet.py --smoke            # CI: assert zero pickled bytes
    python examples/dcn_fleet.py --kill             # async root kill + failover drill
    python examples/dcn_fleet.py --compression topk8

The parent allocates one coordinator port, spawns workers (re-executing this
file with ``--worker PID``), and aggregates each worker's ``RESULT`` line.
``--json`` restricts parent stdout to a single merged JSON object — the
machine seam ``bench_gossip.py --dcn`` builds its honest ``dcn`` row from.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--procs", type=int, default=2, help="world size (OS processes)")
    ap.add_argument("--nodes-per-proc", type=int, default=1)
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--plane", choices=("dcn", "bytes"), default="dcn")
    ap.add_argument("--compression", choices=("none", "int8", "topk8"), default="none")
    ap.add_argument("--smoke", action="store_true",
                    help="CI mode: small fleet + hard zero-pickled-bytes asserts")
    ap.add_argument("--kill", action="store_true",
                    help="async failover drill: hard-kill the global-root process "
                         "mid-experiment (forces --procs 2, --nodes-per-proc 1)")
    ap.add_argument("--json", action="store_true",
                    help="parent prints ONE merged JSON object, nothing else")
    ap.add_argument("--worker", type=int, default=None, help=argparse.SUPPRESS)
    ap.add_argument("--coord-port", type=int, default=None, help=argparse.SUPPRESS)
    return ap.parse_args(argv)


# ---------------------------------------------------------------- worker ----


def run_worker(args) -> None:
    pid = args.worker
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the chip tunnel
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{args.coord_port}"
    os.environ["JAX_NUM_PROCESSES"] = str(args.procs)
    os.environ["JAX_PROCESS_ID"] = str(pid)

    from p2pfl_tpu.parallel.distributed import init_multihost, kv_client

    info = init_multihost()
    assert info["initialized"] and info["process_count"] == args.procs, info

    import jax
    import jax.numpy as jnp
    import numpy as np

    from p2pfl_tpu.communication.dcn import dcn_stats
    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
    from p2pfl_tpu.communication.ici import ici_stats
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings
    from p2pfl_tpu.utils import wait_to_finish

    Settings.WEIGHTS_PLANE = args.plane
    Settings.WIRE_COMPRESSION = args.compression
    if args.kill:
        Settings.FEDERATION_MODE = "async"
        Settings.FEDBUFF_K = 2

    total = args.procs * args.nodes_per_proc
    base_grpc = args.coord_port + 1

    def addr_of(index: int) -> str:
        return f"127.0.0.1:{base_grpc + index}"

    # the kill drill victimizes process 1 but the failover story needs the
    # victim to host the GLOBAL ROOT (federation/routing.py: first live
    # member in address order) — so swap the two processes' address slots
    def my_indices():
        if args.kill:
            return [1 - pid]  # pid 1 → addr slot 0 (the root), pid 0 → slot 1
        return [pid * args.nodes_per_proc + j for j in range(args.nodes_per_proc)]

    client = kv_client()

    def barrier(name: str) -> None:
        client.wait_at_barrier(f"dcn_fleet_{name}", 180_000)

    data = FederatedDataset.synthetic_mnist(
        n_train=128 * max(2, total), n_test=64, seed=7
    )
    nodes = []
    for idx in my_indices():
        learner = JaxLearner(
            mlp(seed=idx), data.partition(idx, total), batch_size=32
        )
        node = Node(learner=learner, protocol=GrpcProtocol(addr_of(idx)))
        node.start()
        nodes.append(node)
    barrier("up")

    # one dialer per edge (links are bidirectional); success = membership
    all_addrs = [addr_of(i) for i in range(total)]
    for node in nodes:
        for other in all_addrs:
            if other <= node.addr:
                continue
            for _ in range(200):
                if node.connect(other) or other in node.get_neighbors(only_direct=True):
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(f"{node.addr} never connected to {other}")
    deadline = time.time() + 60
    while any(len(n.get_neighbors(only_direct=True)) < total - 1 for n in nodes):
        if time.time() > deadline:
            raise RuntimeError("overlay convergence timeout")
        time.sleep(0.1)
    barrier("mesh")

    t0 = time.monotonic()
    if pid == 0:
        # in the kill drill pid 0 holds slot 1 and survives; otherwise the
        # first node everywhere — either way ONE initiator
        nodes[0].set_start_learning(rounds=args.rounds, epochs=args.epochs)

    if args.kill and pid == 1:
        # the victim: wait until the experiment (and the init-model DCN
        # payload) reached us, then die without any goodbye
        deadline = time.time() + 120
        while nodes[0].state.round is None and time.time() < deadline:
            time.sleep(0.05)
        assert nodes[0].state.round is not None, "experiment never started"
        nodes[0].state.model_initialized_event.wait(30)
        time.sleep(0.5)
        print(f"VICTIM {pid}: dying hard", flush=True)
        os._exit(9)

    wait_to_finish(nodes, timeout=120 + 120 * args.rounds)
    wall = time.monotonic() - t0

    fp = sum(
        float(np.sum(np.abs(np.asarray(x, dtype=np.float32))))
        for x in jax.tree.leaves(nodes[0].learner.get_parameters())
    )
    weights_bytes = sum(
        dict(n.protocol.wire_stats).get("weights_bytes", 0) for n in nodes
    )
    result = {
        "pid": pid,
        "plane": args.plane,
        "compression": args.compression,
        "nodes": len(nodes),
        "rounds": args.rounds,
        "wall_s": round(wall, 3),
        "round_s": round(wall / max(1, args.rounds), 3),
        "weights_bytes_grpc": weights_bytes,
        "fingerprint": fp,
        "dcn": dcn_stats(),
        "ici_shard_sends": ici_stats()["shard_sends"],
    }

    if not args.kill:
        # every process ends holding the same diffused aggregate
        from jax.experimental.multihost_utils import process_allgather

        got = process_allgather(jnp.float32(fp))
        # >2 contributors fold the same aggregate set in per-node arrival
        # order — float32 reassociation, not a transport divergence. Lossy
        # codecs widen it: each node folds its OWN exact params against the
        # peers' quantized deltas (identical on the byte path), so int8/
        # topk8 spreads carry the quantization error, not a plane bug.
        rel_tol = 1e-5 if args.compression == "none" else 1e-2
        spread = float(np.max(got)) - float(np.min(got))
        assert spread <= rel_tol * max(1.0, abs(float(np.max(got)))), got
        if args.plane == "dcn":
            s = result["dcn"]
            assert s["dcn_sends"] > 0 and s["dcn_recvs"] > 0, s
            if args.compression == "topk8":
                # delta payloads whose anchor round the receiver doesn't
                # hold yet fall back loudly (anchor_round_mismatch — the
                # byte path's AnchorMismatchError-skip semantics); allow
                # those transient early-round edges, nothing more
                assert s["fallback_bytes"] <= args.rounds, s
            else:
                assert s["fallback_bytes"] == 0, s
                # the tentpole: zero pickled model bytes on gRPC
                assert weights_bytes == 0, result
        else:
            assert weights_bytes > 0, result

    print("RESULT " + json.dumps(result), flush=True)
    for n in nodes:
        n.stop()
    if args.kill:
        # skip atexit: jax.distributed's shutdown barrier aborts when a
        # world member died mid-run — which is this drill's whole point
        print(f"OK fleet process {pid}", flush=True)
        os._exit(0)
    print(f"OK fleet process {pid}", flush=True)


# ---------------------------------------------------------------- parent ----


def run_parent(args) -> int:
    if args.smoke:
        args.procs, args.nodes_per_proc, args.rounds = 2, 1, 2
    if args.kill:
        args.procs, args.nodes_per_proc = 2, 1
        args.rounds = max(args.rounds, 3)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord_port = s.getsockname()[1]

    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_POOL_IPS")
    }
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    cmd_base = [
        sys.executable, os.path.abspath(__file__),
        "--procs", str(args.procs),
        "--nodes-per-proc", str(args.nodes_per_proc),
        "--rounds", str(args.rounds),
        "--epochs", str(args.epochs),
        "--plane", args.plane,
        "--compression", args.compression,
        "--coord-port", str(coord_port),
    ]
    if args.kill:
        cmd_base.append("--kill")
    procs = [
        subprocess.Popen(
            cmd_base + ["--worker", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in range(args.procs)
    ]
    outs = []
    timeout = 180 + 150 * args.rounds
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            print("FLEET HUNG — coordinator never formed or a worker stalled",
                  file=sys.stderr)
            return 2
        outs.append(out)

    results, ok = [], True
    for pid, (p, out) in enumerate(zip(procs, outs)):
        expected_rc = 9 if (args.kill and pid == 1) else 0
        if p.returncode != expected_rc:
            ok = False
            print(f"worker {pid} rc={p.returncode} (expected {expected_rc}):\n"
                  + out[-3000:], file=sys.stderr)
            continue
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
    if args.kill and ok:
        survivor = [r for r in results if r["pid"] == 0]
        if not survivor or survivor[0]["dcn"]["dcn_sends"] < 1:
            ok = False
            print("kill drill: survivor missing or no DCN traffic pre-kill",
                  file=sys.stderr)

    merged = {
        "plane": args.plane,
        "compression": args.compression,
        "procs": args.procs,
        "nodes_per_proc": args.nodes_per_proc,
        "rounds": args.rounds,
        "kill": args.kill,
        "ok": ok,
        "round_s": max((r["round_s"] for r in results), default=None),
        "weights_bytes_grpc": sum(r["weights_bytes_grpc"] for r in results),
        "dcn_sends": sum(r["dcn"]["dcn_sends"] for r in results),
        "dcn_recvs": sum(r["dcn"]["dcn_recvs"] for r in results),
        "bytes_moved_device": sum(r["dcn"]["bytes_moved"] for r in results),
        "fallback_bytes": sum(r["dcn"]["fallback_bytes"] for r in results),
        "ici_shard_sends": sum(r["ici_shard_sends"] for r in results),
        "workers": results,
    }
    if args.json:
        print(json.dumps(merged))
    else:
        print(f"\n=== DCN fleet: {args.procs} procs × {args.nodes_per_proc} nodes, "
              f"plane={args.plane}, compression={args.compression} ===")
        for r in sorted(results, key=lambda r: r["pid"]):
            print(f"  proc {r['pid']}: round_s={r['round_s']:.2f} "
                  f"dcn_sends={r['dcn']['dcn_sends']} dcn_recvs={r['dcn']['dcn_recvs']} "
                  f"device_bytes={r['dcn']['bytes_moved']} "
                  f"grpc_weight_bytes={r['weights_bytes_grpc']} "
                  f"fallbacks={r['dcn']['fallback_bytes']} "
                  f"ici_sends={r['ici_shard_sends']}")
        verdict = "PASS" if ok else "FAIL"
        if args.kill:
            print(f"  kill drill: victim died, survivor finished → {verdict}")
        else:
            print(f"  fleet {verdict}: zero-pickled-bytes="
                  f"{merged['weights_bytes_grpc'] == 0}")
    return 0 if ok else 1


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.worker is not None:
        run_worker(args)
        return 0
    return run_parent(args)


if __name__ == "__main__":
    sys.exit(main())
