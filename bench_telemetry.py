"""Telemetry smoke: drive a 2-node round with the flight recorder on,
export the Chrome trace, validate it against the trace-event schema, print
the RoundReport, and bound the recorder's overhead.

CI runs this as the `ci.yml` telemetry step:

    JAX_PLATFORMS=cpu python bench_telemetry.py --out /tmp/telemetry-smoke

The overhead assertion here is a SMOKE bound (default 20%, plus an
absolute floor for protocol-tick quantization) — shared-runner wall-clock
noise swamps the real figure; the honest ≤5% measurement lives in
bench_suite config1's `telemetry` split (BENCH_SUITE.json), averaged over
more rounds on a quiet machine. This step exists to catch a regression
that makes the recorder *expensive*, not to re-measure the budget.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def run_federation(rounds: int, telemetry_on: bool) -> float:
    """One fresh 2-node DummyLearner federation; returns wall seconds."""
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning.learner import DummyLearner
    from p2pfl_tpu.management.telemetry import telemetry
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    MemoryRegistry.reset()
    prev = Settings.TELEMETRY_ENABLED
    Settings.TELEMETRY_ENABLED = telemetry_on
    if telemetry_on:
        telemetry.reset_spans()
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(2)]
    try:
        for n in nodes:
            n.start()
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 1, only_direct=True, wait=10)
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=rounds, epochs=1)
        wait_to_finish(nodes, timeout=120)
        return time.monotonic() - t0
    finally:
        Settings.TELEMETRY_ENABLED = prev
        for n in nodes:
            n.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/telemetry-smoke", help="trace/report output dir")
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument(
        "--overhead-bound", type=float, default=20.0,
        help="max telemetry-on overhead %% (smoke bound — see module docstring)",
    )
    args = ap.parse_args()

    from p2pfl_tpu.management.logger import logger
    from p2pfl_tpu.settings import set_test_settings

    set_test_settings()
    logger.set_level("ERROR")

    from p2pfl_tpu.management.telemetry import (
        dump_flight_record,
        telemetry,
        validate_chrome_trace,
    )

    # 0. warm-up federation OUTSIDE any timer: the first run pays one-time
    # costs (eager-op compiles, thread-pool spin-up) that would otherwise
    # bill entirely to whichever mode runs first
    run_federation(1, telemetry_on=False)

    # 1. telemetry-on round loop → trace + report artifacts
    wall_on = run_federation(args.rounds, telemetry_on=True)
    paths = dump_flight_record(args.out)
    doc = json.load(open(paths[0]))
    n_events = validate_chrome_trace(doc)
    print(f"trace: {paths[0]} ({n_events} events) — schema OK")

    reports = json.load(open(paths[1]))
    if not reports:
        print("FAIL: no round reports produced", file=sys.stderr)
        return 1
    for rep in reports:
        crit = rep["critical_path"]
        print(
            f"round {rep['round']}: wall {rep['wall_s']:.2f}s, "
            f"critical node {crit['node']} ({crit['stage']})"
        )
    rep0 = telemetry.round_report(0)
    if not rep0.per_node:
        print("FAIL: round 0 report attributed no spans", file=sys.stderr)
        return 1
    print(rep0.describe())

    # sanity: wire ctx linked at least one cross-thread/cross-node edge
    spans = telemetry.spans()
    recv_linked = [s for s in spans if s.name.startswith("recv:") and s.parent_id]
    if not recv_linked:
        print("FAIL: no recv spans carried a wire trace context", file=sys.stderr)
        return 1
    print(f"wire trace ctx: {len(recv_linked)} receiver spans linked to sender spans")

    # 2. telemetry-off loop → overhead smoke bound
    wall_off = run_federation(args.rounds, telemetry_on=False)
    overhead_pct = (wall_on - wall_off) / wall_off * 100.0
    # absolute floor: at sub-second rounds a single protocol tick (50-100ms)
    # of scheduling jitter exceeds any honest percentage
    tolerance_s = max(wall_off * args.overhead_bound / 100.0, 0.5)
    print(
        f"round loop: on={wall_on:.2f}s off={wall_off:.2f}s "
        f"({overhead_pct:+.1f}%, smoke bound {args.overhead_bound:.0f}% / {tolerance_s:.2f}s)"
    )
    if wall_on - wall_off > tolerance_s:
        print("FAIL: telemetry overhead exceeded the smoke bound", file=sys.stderr)
        return 1
    print("telemetry smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
