# Container packaging for p2pfl_tpu (reference parity: /root/reference/Dockerfile:1).
#
# Two build modes:
#   docker build -t p2pfl-tpu .                           # CPU (jax[cpu]) — simulation / CI
#   docker build -t p2pfl-tpu --build-arg JAX_EXTRA=tpu . # Cloud TPU VM (libtpu via jax[tpu])
#
# The virtual multi-node simulation needs no accelerator:
#   docker run -e JAX_PLATFORMS=cpu \
#     -e XLA_FLAGS=--xla_force_host_platform_device_count=8 \
#     p2pfl-tpu python -m pytest tests/ -q

FROM python:3.11-slim

ARG JAX_EXTRA=cpu

ENV PYTHONUNBUFFERED=1 \
    PIP_DISABLE_PIP_VERSION_CHECK=on \
    PIP_DEFAULT_TIMEOUT=100

# g++ builds the optional native codec (p2pfl_tpu/native/codec.cpp);
# everything degrades to the numpy fallback without it.
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY p2pfl_tpu ./p2pfl_tpu
COPY tests ./tests
COPY bench.py bench_suite.py ./

RUN pip install "jax[${JAX_EXTRA}]" && \
    pip install -e ".[grpc,checkpoint,monitor,test]"

# Pre-build the native codec so first use doesn't pay the compile
# (quantize() builds the .so on first call when g++ is present). Drop any
# host-built .so first — one compiled against the host's arch/glibc would
# fail to dlopen here but its presence suppresses the rebuild.
RUN rm -f p2pfl_tpu/native/*.so && \
    python -c "import numpy as np; from p2pfl_tpu import native; \
native.quantize(np.zeros(8, np.float32)); \
assert native._load() is not None, 'native codec failed to build'; \
print('native codec ready')"

CMD ["python", "-m", "p2pfl_tpu.cli", "experiment", "list"]
