"""Gossip data-plane benchmark: encode-once payload cache + concurrent fan-out.

Three measurements, all on the in-memory transport with the byte path forced
(``Settings.MEMORY_WIRE_CODEC=True`` — payloads are really encoded, shipped
and materialized, just without sockets):

1. **Codec microbench** — ``encode_params``/``decode_params`` wall-clock per
   payload for the MLP and transformer configs, per wire compression.
2. **Encode accounting** — encode-pipeline invocations per node per round on
   a federation run, plus the payload cache's hit/miss counters as exported
   through ``logger.get_comm_metrics()``. Pre-overhaul behavior was one
   encode per candidate per tick (O(neighbors × ticks)); with the cache it
   is bounded by distinct payload contents per round — own model versions
   (~2: post-fit contribution + post-aggregation diffusion) plus distinct
   partial-aggregation contents.
3. **Slow-peer round time** — end-to-end wall-clock of a federated round on
   an 8-node federation with one peer whose receive path stalls, comparing
   the pre-overhaul data plane (sequential sends, no cache, no send budget:
   ``GOSSIP_SEND_WORKERS=1``, ``GOSSIP_PAYLOAD_CACHE=False``, huge
   ``GOSSIP_SEND_TIMEOUT``) against the shipped defaults (4 send workers,
   cache on, 0.5 s budget).
4. **Compression split** — host (numpy argpartition + native quantize) vs
   device (``ops/compression.py`` fused jit) producer per compression mode:
   encode wall-clock, payload bytes, and the bytes that cross device→host
   per encode (the host producer pulls the FULL fp32 model + anchor; the
   device producer only the compressed ``(idx, q, scale)`` buffers), with a
   decode-parity check between both producers' frames.

``--smoke`` runs a shrunken federation and asserts the encode-once
invariant (encodes per node-round bounded by distinct contents, cache hits
present) plus the compression-split invariants (host/device frames decode
to the same tree within quantization tolerance; device topk8 D2H stays
~payload-sized, not model-sized) — the CI guard that keeps the cache and
the device codec from silently regressing.

usage: JAX_PLATFORMS=cpu python bench_gossip.py [--smoke] [--out BENCH_GOSSIP.json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def _make_model(name: str, seed: int = 0):
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    if name == "mlp":
        return mlp(seed=seed)
    cfg = TransformerConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
        ffn_hidden=128, lora_rank=0,
    )
    return tiny_transformer(seq_len=32, cfg=cfg, seed=seed)


def bench_codec(repeats: int = 5) -> dict:
    """encode/decode wall-clock per payload, per model config × compression."""
    from p2pfl_tpu.learning.weights import decode_params, encode_params

    out: dict = {}
    for name in ("mlp", "transformer"):
        model = _make_model(name)
        params = {k: np.asarray(v) for k, v in _flatten(model.params).items()}
        anchor = {k: v - 0.01 if v.dtype.kind == "f" else v for k, v in params.items()}
        entry: dict = {"param_bytes": int(sum(v.nbytes for v in params.values()))}
        for comp in ("none", "int8", "topk8"):
            kw = {"compression": comp}
            if comp == "topk8":
                kw.update(anchor=anchor, anchor_tag="0:0")
            payload = encode_params(params, **kw)  # warmup
            t0 = time.perf_counter()
            for _ in range(repeats):
                payload = encode_params(params, **kw)
            enc_ms = (time.perf_counter() - t0) / repeats * 1e3
            dkw = {"anchor": anchor, "anchor_tag": "0:0"} if comp == "topk8" else {}
            t0 = time.perf_counter()
            for _ in range(repeats):
                decode_params(payload, **dkw)
            dec_ms = (time.perf_counter() - t0) / repeats * 1e3
            entry[comp] = {
                "payload_bytes": len(payload),
                "encode_ms": round(enc_ms, 3),
                "decode_ms": round(dec_ms, 3),
            }
        out[name] = entry
    return out


def _flatten(tree):
    from p2pfl_tpu.learning.weights import _flatten_named

    return _flatten_named(tree)


def _wide_tree(n_params: int = 4_000_000, seed: int = 0):
    """Synthetic multi-leaf fp32 tree (device-resident) for the compression
    split — big enough that codec throughput, not dispatch overhead,
    dominates."""
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    per = n_params // 4
    return {
        f"block{i}/w": jnp.asarray(rng.normal(size=per).astype(np.float32))
        for i in range(4)
    }


def bench_compression(repeats: int = 5, smoke: bool = False) -> dict:
    """Host vs device producer: encode wall-clock, payload bytes, D2H bytes.

    Returns per-model entries like ``topk8_host`` / ``topk8_device`` plus
    ``*_device_speedup``; parity between the two producers' frames is
    asserted (decoded trees agree within the int8 quantization tolerance —
    the wire-format invariance contract).

    Backend caveat (recorded in the output): on the CPU backend "device"
    IS the host CPU — the D2H pull the device producer eliminates is a
    near-free memcpy here, and XLA:CPU's exact TopK (a partial sort) runs
    5–10× slower than numpy's introselect, so ``topk8_device_speedup`` < 1
    on CPU is expected. The structural numbers (``d2h_bytes_per_encode``
    ~payload-sized vs the host's full fp32 model+anchor pull) are
    backend-independent; on a TPU backend the selection is
    hardware-parallel and the host path's per-leaf PCIe pulls dominate.
    """
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.learning import weights as W
    from p2pfl_tpu.settings import Settings

    configs = {"mlp": None} if smoke else {"mlp": None, "wide_4m": None}
    out: dict = {"backend": jax.default_backend()}
    prev_flag = Settings.WIRE_COMPRESSION_DEVICE
    try:
        for name in configs:
            if name == "wide_4m":
                params = _wide_tree()
            else:
                params = {k: jnp.asarray(v) for k, v in _flatten(_make_model(name).params).items()}
            # proportional perturbation: distinct |delta| per coordinate, so
            # top-k selection is deterministic (no argpartition/top_k
            # tie-break divergence) and the workload is non-degenerate
            anchor = {
                k: (v * 0.99 if np.dtype(v.dtype).kind == "f" else v)
                for k, v in params.items()
            }
            raw_bytes = int(
                sum(v.size * np.dtype(v.dtype).itemsize for v in params.values())
            )
            entry: dict = {"param_bytes": raw_bytes}
            for comp in ("int8", "topk8"):
                kw = {"compression": comp}
                if comp == "topk8":
                    kw.update(anchor=anchor, anchor_tag="0:0")
                payloads = {}
                for mode, flag in (("host", False), ("device", True)):
                    Settings.WIRE_COMPRESSION_DEVICE = flag
                    payload = W.encode_params(params, **kw)  # warmup (jit compile)
                    W.reset_wire_stats()
                    t0 = time.perf_counter()
                    for _ in range(repeats):
                        payload = W.encode_params(params, **kw)
                    ms = (time.perf_counter() - t0) / repeats * 1e3
                    stats = W.wire_stats()
                    payloads[mode] = payload
                    entry[f"{comp}_{mode}"] = {
                        "encode_ms": round(ms, 3),
                        "payload_bytes": len(payload),
                        "d2h_bytes_per_encode": stats["d2h_bytes"] // repeats,
                    }
                entry[f"{comp}_device_speedup"] = round(
                    entry[f"{comp}_host"]["encode_ms"]
                    / max(entry[f"{comp}_device"]["encode_ms"], 1e-9),
                    2,
                )
                # wire-format invariance: both frames through the ONE decoder
                Settings.WIRE_COMPRESSION_DEVICE = False
                dkw = {"anchor": anchor, "anchor_tag": "0:0"} if comp == "topk8" else {}
                ref = W.decode_params(payloads["host"], **dkw)
                cross = W.decode_params(payloads["device"], **dkw)
                for k in ref:
                    np.testing.assert_allclose(
                        np.asarray(ref[k], np.float32),
                        np.asarray(cross[k], np.float32),
                        atol=0.05,
                        err_msg=f"host/device frame parity broke at {k} ({comp})",
                    )
            out[name] = entry
    finally:
        Settings.WIRE_COMPRESSION_DEVICE = prev_flag
    return out


def run_federation(
    n_nodes: int,
    rounds: int,
    model_name: str = "mlp",
    slow_peer_delay: float = 0.0,
    workers: int = 4,
    cache: bool = True,
    send_timeout: float = 0.5,
    train_set_size: int = 0,
    weights_plane: str = "bytes",
) -> dict:
    """One timed federation run on the in-memory byte path.

    Returns round wall-clock plus encode/cache/send accounting. epochs=0
    keeps device compute out of the measurement — what remains IS the
    gossip data plane (init push, partial gossip, diffusion).

    ``weights_plane="ici"`` re-routes model payloads through the
    shard-native ICI plane (``communication/ici.py`` — the ppermute
    fallback on this CPU bench): the byte path below stays armed as the
    per-peer fallback, so the row's host-byte counters measure what the
    plane actually kept off the host.
    """
    from p2pfl_tpu.communication import ici
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning import weights as W
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.management.logger import logger
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings, set_test_settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    set_test_settings()
    logger.set_level("ERROR")
    Settings.MEMORY_WIRE_CODEC = True
    Settings.WEIGHTS_PLANE = weights_plane
    Settings.GOSSIP_SEND_WORKERS = workers
    Settings.GOSSIP_PAYLOAD_CACHE = cache
    Settings.GOSSIP_SEND_TIMEOUT = send_timeout
    ici.ShardPlaneRegistry.reset()
    ici.reset_ici_stats()
    W.reset_wire_stats()
    if train_set_size:
        # slow-peer configs elect EVERYONE so the stalled node is a
        # train-set member being gossiped partials every tick — the
        # worst case the fan-out is built for
        Settings.TRAIN_SET_SIZE = train_set_size
    MemoryRegistry.reset()
    # atomic snapshot_and_reset (not the old get+reset pair): counters a
    # previous scenario's still-draining threads land between the two
    # calls can no longer leak into this scenario's window
    logger.snapshot_and_reset_comm_metrics()

    if model_name == "transformer":
        full = FederatedDataset.synthetic_lm(
            n_train=n_nodes * 32, n_test=32, seq_len=32, vocab_size=256
        )
    else:
        full = FederatedDataset.synthetic_mnist(n_train=n_nodes * 64, n_test=64)
    nodes = []
    for i in range(n_nodes):
        learner = JaxLearner(
            _make_model(model_name, seed=i), full.partition(i, n_nodes), batch_size=16
        )
        nodes.append(Node(learner=learner))
    try:
        for node in nodes:
            node.start()
        for node in nodes:
            full_connection(node, nodes)
        wait_convergence(nodes, n_nodes - 1, only_direct=True, wait=15)

        if slow_peer_delay > 0:
            slow = nodes[-1]
            orig = slow.protocol.handle_weights

            def slow_handle(env):
                time.sleep(slow_peer_delay)
                return orig(env)

            slow.protocol.handle_weights = slow_handle

        encodes_before = W.encode_call_count()
        t0 = time.perf_counter()
        nodes[0].set_start_learning(rounds=rounds, epochs=0)
        # with a stalled peer injected, the figure of merit is when the
        # HEALTHY nodes close their rounds — the stalled peer is slow by
        # construction (it pays its own sleeps) and catches up afterwards
        wait_to_finish(nodes[:-1] if slow_peer_delay > 0 else nodes, timeout=300)
        wall_s = time.perf_counter() - t0
        encodes = W.encode_call_count() - encodes_before
        # harvest atomically: the federation's heartbeat/gossip threads are
        # still incrementing — a get+reset pair here would lose whatever
        # lands in the gap (and double-count it into the next scenario)
        comm = logger.snapshot_and_reset_comm_metrics()

        def total(metric):
            return int(sum(m.get(metric, 0) for m in comm.values()))

        wire = W.wire_stats()
        ici_stats = ici.ici_stats()
        return {
            "n_nodes": n_nodes,
            "rounds": rounds,
            "model": model_name,
            "workers": workers,
            "cache": cache,
            "send_timeout_s": send_timeout,
            "slow_peer_delay_s": slow_peer_delay,
            "weights_plane": weights_plane,
            "round_wall_s": round(wall_s / rounds, 3),
            "total_wall_s": round(wall_s, 3),
            "encode_calls": encodes,
            "encode_calls_per_node_round": round(encodes / (n_nodes * rounds), 3),
            "cache_hits": total("encode_cache_hit"),
            "cache_misses": total("encode_cache_miss"),
            "sends_ok": total("gossip_send_ok"),
            "send_timeouts": total("gossip_send_timeout"),
            "inflight_skips": total("gossip_send_inflight_skip"),
            # bytes-over-host (the ICI row's headline): payload bytes the
            # encode pipeline materialized + D2H it pulled, plus the
            # shard plane's own accounting and the receiver-side D2D
            # fix-up copies FedAvg counted (ICI contract: zero)
            "host_payload_bytes": wire["payload_bytes"],
            "host_d2h_bytes": wire["d2h_bytes"],
            "ici_shard_sends": ici_stats["shard_sends"],
            "ici_bytes_moved": ici_stats["bytes_moved"],
            "ici_fallback_bytes": ici_stats["fallback_bytes"],
            "ici_align_violations": ici_stats["align_violations"],
            "tree_align_copies": total("tree_align_copies"),
        }
    finally:
        for node in nodes:
            node.stop()
        MemoryRegistry.reset()
        ici.ShardPlaneRegistry.reset()
        Settings.MEMORY_WIRE_CODEC = False
        Settings.WEIGHTS_PLANE = "bytes"
        Settings.GOSSIP_PAYLOAD_CACHE = True
        Settings.GOSSIP_SEND_WORKERS = 4


# distinct payload contents a node can produce in one epochs=0 round: the
# init-model push, its (unfit) contribution, one combined partial, and the
# post-aggregation diffusion — the encode-once ceiling asserted in CI
MAX_ENCODES_PER_NODE_ROUND = 4.0


def _stream_worker(mode: str, size_mb: int, chunk_mb: float) -> dict:
    """One weights transfer over REAL loopback gRPC in a fresh process.

    Runs out-of-process so ``ru_maxrss`` is an honest per-mode peak — the
    parent (and the other mode) never pollutes the high-water mark. Both
    endpoints live in this one process (loopback needs a server), so the
    peak covers sender + receiver; the structural gap stays visible: the
    unary path holds payload + gRPC message + receiver bytes + decode
    copies concurrently, the streamed path holds the chunk list plus a
    window of in-flight frames plus the incrementally decoded leaves.
    """
    import resource
    import threading

    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
    from p2pfl_tpu.learning import weights as W
    from p2pfl_tpu.learning.weights import ModelUpdate
    from p2pfl_tpu.management.logger import logger
    from p2pfl_tpu.settings import Settings

    logger.set_level("ERROR")
    Settings.HEARTBEAT_PERIOD = 30.0
    Settings.GRPC_TIMEOUT = 120.0
    Settings.WIRE_CHUNK_MB = chunk_mb
    if mode == "stream":
        Settings.WIRE_STREAM_ENABLED = True
        Settings.WIRE_STREAM_THRESHOLD = 1.0
    else:
        Settings.WIRE_STREAM_ENABLED = False

    leaf = 4 * 1024 * 1024  # 4 MB fp32 leaves
    n_leaves = max(1, (size_mb * 1024 * 1024) // leaf)
    rng = np.random.default_rng(0)
    tree = {
        f"block{i}/w": rng.normal(size=leaf // 4).astype(np.float32)
        for i in range(n_leaves)
    }

    a, b = GrpcProtocol("127.0.0.1:0"), GrpcProtocol("127.0.0.1:0")
    a.start()
    b.start()
    assert a.connect(b.get_address())

    done = threading.Event()

    class _Sink:
        def get_name(self):
            return "add_model"

        def execute(self, source, round, *args, **kwargs):  # noqa: A002
            done.set()

    b.add_command(_Sink())

    # overlap probe: timestamp every chunk as the receiver's decoder pulls
    # it — on the streamed path decode work is spread across
    # [first_chunk, last_chunk] while bytes are still arriving; unary
    # decodes strictly after the full payload lands (overlap window = 0)
    chunk_ts: list = []
    orig_stream = b.handle_weights_stream

    def probed(env, chunks):
        def ticking():
            for c in chunks:
                chunk_ts.append(time.perf_counter())
                yield c

        return orig_stream(env, ticking())

    b.handle_weights_stream = probed

    try:
        env = a.build_weights("add_model", 0, ModelUpdate(tree, ["bench"], 1))
        payload_bytes = len(env.update.encode())  # warm + exact size
        # best-of-3 transfers (single loopback runs are ±15% noisy); RSS
        # high-water marks accumulate across all repeats in both modes
        walls = []
        rss_before_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        for _ in range(3):
            env.update.encoded = None  # both modes re-encode inside the send
            done.clear()
            del chunk_ts[:]
            t0 = time.perf_counter()
            ok = a.send(b.get_address(), env)
            send_done = time.perf_counter()
            assert ok, f"{mode} transfer failed"
            assert done.wait(timeout=60), "receiver never dispatched the update"
            walls.append(time.perf_counter() - t0)
        wall_s = min(walls)
        rss_after_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        overlap_s = (
            min(send_done, chunk_ts[-1]) - chunk_ts[0] if len(chunk_ts) > 1 else 0.0
        )
        return {
            "mode": mode,
            "payload_mb": round(payload_bytes / 1e6, 1),
            "wall_s": round(wall_s, 3),
            "mb_per_s": round(payload_bytes / 1e6 / wall_s, 1),
            "peak_rss_mb": round(rss_after_kb / 1024, 1),
            "transfer_rss_growth_mb": round((rss_after_kb - rss_before_kb) / 1024, 1),
            "stream_sends": a.wire_stats["stream_sends"],
            "stream_chunks": a.wire_stats["stream_chunks"],
            "stream_fallback_unary": a.wire_stats["stream_fallback_unary"],
            "recv_scratch_peak_mb": round(
                W.wire_stats()["stream_peak_scratch_bytes"] / 1e6, 2
            ),
            "wire_decode_overlap_s": round(overlap_s, 3),
        }
    finally:
        a.stop()
        b.stop()


def bench_stream(size_mb: int = 104, chunk_mb: float = 4.0) -> dict:
    """Streamed vs option-raised-unary weights transfer over loopback gRPC.

    Each mode runs in its own subprocess (``--stream-worker``) so peak RSS
    is per-mode truth. The streamed row's claims: wall-clock at or below
    the unary path (pipelined wire/decode overlap), receiver scratch
    bounded by chunk + largest leaf — NOT payload-sized — and zero
    fallbacks.
    """
    script = os.path.abspath(__file__)
    rows = {}
    for mode in ("unary", "stream"):
        proc = subprocess.run(
            [sys.executable, script, "--stream-worker", mode,
             "--size-mb", str(size_mb), "--chunk-mb", str(chunk_mb)],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, (
            f"stream worker mode={mode} rc={proc.returncode}:\n"
            f"{proc.stdout[-3000:]}\n{proc.stderr[-3000:]}"
        )
        rows[mode] = json.loads(proc.stdout.strip().splitlines()[-1])
    st, un = rows["stream"], rows["unary"]
    assert st["stream_sends"] >= 1 and st["stream_fallback_unary"] == 0, st
    assert un["stream_sends"] == 0, un
    assert st["wire_decode_overlap_s"] > 0, (
        "streamed transfer showed no wire/decode overlap window"
    )
    assert st["recv_scratch_peak_mb"] * 4 < st["payload_mb"], (
        f"receiver scratch {st['recv_scratch_peak_mb']} MB is not bounded "
        f"vs the {st['payload_mb']} MB payload"
    )
    return {
        "unary": un,
        "stream": st,
        "stream_speedup": round(un["wall_s"] / max(st["wall_s"], 1e-9), 2),
        "peak_rss_saved_mb": round(
            un["transfer_rss_growth_mb"] - st["transfer_rss_growth_mb"], 1
        ),
        "chunk_mb": chunk_mb,
        "backend": "loopback gRPC, both endpoints in one subprocess per mode",
    }


def _dcn_fleet(plane: str, rounds: int = 2) -> dict:
    """One 2-process × 1-node fleet via ``examples/dcn_fleet.py --json``.

    The fleet MUST run out-of-process: each worker is a member of one
    ``jax.distributed`` world, and ``jax.distributed.initialize`` is
    once-per-process — the bench parent (which already holds a backend)
    can only orchestrate.
    """
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "examples", "dcn_fleet.py")
    proc = subprocess.run(
        [sys.executable, script, "--json", "--plane", plane,
         "--procs", "2", "--nodes-per-proc", "1", "--rounds", str(rounds)],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, (
        f"dcn_fleet plane={plane} rc={proc.returncode}:\n{proc.stdout[-3000:]}"
        f"\n{proc.stderr[-3000:]}"
    )
    merged = json.loads(proc.stdout.strip().splitlines()[-1])
    assert merged["ok"], merged
    return merged


def bench_dcn(rounds: int = 2) -> dict:
    """DCN weights plane vs the byte path across a REAL process boundary:
    the same 2-process federation once with cross-process model payloads as
    device arrays over the distributed world's collectives, once pickled
    over gRPC. On this CPU anchor the world runs gloo collectives over
    localhost, so round_s is structural (protocol + copies), not an
    interconnect measurement — a TPU pod rides the actual DCN."""
    dcn_row = _dcn_fleet("dcn", rounds=rounds)
    byte_row = _dcn_fleet("bytes", rounds=rounds)
    assert dcn_row["dcn_sends"] > 0, dcn_row
    assert dcn_row["fallback_bytes"] == 0, dcn_row
    assert dcn_row["weights_bytes_grpc"] == 0, dcn_row
    assert byte_row["weights_bytes_grpc"] > 0, byte_row
    return {
        "dcn_plane": dcn_row,
        "grpc_byte_path": byte_row,
        "grpc_weight_bytes": {
            "bytes": byte_row["weights_bytes_grpc"],
            "dcn": dcn_row["weights_bytes_grpc"],
        },
        "device_bytes_moved": {
            "bytes": 0,
            "dcn": dcn_row["bytes_moved_device"],
        },
        "s_per_round": {
            "bytes": byte_row["round_s"],
            "dcn": dcn_row["round_s"],
        },
        "backend": "gloo over localhost (CPU anchor; TPU pods ride the DCN)",
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true", help="small run + invariant asserts (CI)")
    ap.add_argument("--out", default="BENCH_GOSSIP.json")
    ap.add_argument("--stream-worker", choices=("unary", "stream"),
                    help="internal: run one loopback transfer and print JSON")
    ap.add_argument("--size-mb", type=int, default=104)
    ap.add_argument("--chunk-mb", type=float, default=4.0)
    args = ap.parse_args()

    if args.stream_worker:
        print(json.dumps(_stream_worker(args.stream_worker, args.size_mb, args.chunk_mb)))
        return 0

    results: dict = {"smoke": bool(args.smoke)}

    if args.smoke:
        fed = run_federation(n_nodes=3, rounds=1)
        results["federation"] = fed
        assert fed["cache_hits"] >= 1, "payload cache never hit on the byte path"
        assert fed["encode_calls_per_node_round"] <= MAX_ENCODES_PER_NODE_ROUND, (
            f"encode-once regressed: {fed['encode_calls_per_node_round']} encodes "
            f"per node-round (max {MAX_ENCODES_PER_NODE_ROUND}) — the cache is "
            "not being reused across candidates/ticks"
        )
        # device-codec guard: parity is asserted inside bench_compression;
        # on top of it, the device producer's D2H must be ~payload-sized
        comp = bench_compression(repeats=2, smoke=True)
        results["compression"] = comp
        tk_dev = comp["mlp"]["topk8_device"]
        assert tk_dev["d2h_bytes_per_encode"] < comp["mlp"]["param_bytes"] / 4, (
            f"device topk8 encode pulled {tk_dev['d2h_bytes_per_encode']} bytes D2H "
            f"for a {comp['mlp']['param_bytes']}-byte model — the fused encode is "
            "no longer keeping the model on device"
        )
        assert tk_dev["d2h_bytes_per_encode"] < tk_dev["payload_bytes"] * 3, (
            "device topk8 D2H should be on the order of the payload, not the model"
        )
        # ICI weights plane: same fleet, model payloads shard-to-shard —
        # the parity + zero-D2H smoke (the ppermute fallback on CI's CPU)
        ici_fed = run_federation(n_nodes=3, rounds=1, weights_plane="ici")
        results["ici_federation"] = ici_fed
        assert ici_fed["ici_shard_sends"] > 0, "ICI plane never carried a payload"
        assert ici_fed["ici_fallback_bytes"] == 0, (
            f"{ici_fed['ici_fallback_bytes']} co-located sends fell back to bytes"
        )
        assert ici_fed["host_payload_bytes"] == 0 and ici_fed["host_d2h_bytes"] == 0, (
            "ICI round materialized model bytes host-side "
            f"(payload={ici_fed['host_payload_bytes']}, d2h={ici_fed['host_d2h_bytes']})"
            " — the zero-host-bytes contract broke"
        )
        assert ici_fed["encode_calls"] == 0, (
            f"{ici_fed['encode_calls']} byte encodes ran under WEIGHTS_PLANE=ici"
        )
        assert ici_fed["ici_align_violations"] == 0 and ici_fed["tree_align_copies"] == 0, (
            "ICI deliveries needed device fix-up copies — the no-realign "
            "contract broke"
        )
        # DCN weights plane: a real 2-process world, model payloads as
        # device arrays across the process boundary — zero pickled weight
        # bytes on gRPC (the asserts live in bench_dcn / the fleet driver)
        results["dcn_federation"] = bench_dcn(rounds=1)
        # streaming byte plane: a shrunken transfer over real loopback gRPC
        # — the invariant asserts (stream engaged, zero fallbacks, wire/
        # decode overlap observed, receiver scratch bounded) live inside
        # bench_stream; wall-clock claims are left to the full run
        results["stream"] = bench_stream(size_mb=16, chunk_mb=2.0)
        print(json.dumps(results, indent=2))
        print("SMOKE OK: encode-once + device-codec + ICI zero-D2H + "
              "DCN zero-pickled-bytes + stream-overlap invariants hold")
        return 0

    results["codec"] = bench_codec()
    results["compression"] = bench_compression()
    # warm the jit/codec caches so neither timed variant pays first-compile
    run_federation(n_nodes=8, rounds=1)
    results["sequential_nocache"] = run_federation(
        n_nodes=8, rounds=1, slow_peer_delay=2.0, workers=1, cache=False,
        send_timeout=60.0, train_set_size=8,
    )
    results["concurrent_cached"] = run_federation(
        n_nodes=8, rounds=1, slow_peer_delay=2.0, workers=4, cache=True,
        send_timeout=0.25, train_set_size=8,
    )
    results["transformer_federation"] = run_federation(
        n_nodes=8, rounds=1, model_name="transformer"
    )
    seq, conc = results["sequential_nocache"], results["concurrent_cached"]
    results["round_speedup_with_slow_peer"] = round(
        seq["round_wall_s"] / max(conc["round_wall_s"], 1e-9), 2
    )
    # ICI weights plane vs the memory byte path: same fleet, same rounds —
    # bytes-over-host and s/round are the row's two claims (on this CPU
    # anchor "ICI" is the ppermute fallback over virtual devices, so the
    # wall-clock is structural, not an interconnect measurement)
    mem_row = run_federation(n_nodes=4, rounds=2)
    ici_row = run_federation(n_nodes=4, rounds=2, weights_plane="ici")
    results["ici"] = {
        "memory_byte_path": mem_row,
        "ici_plane": ici_row,
        "host_payload_bytes": {
            "memory": mem_row["host_payload_bytes"],
            "ici": ici_row["host_payload_bytes"],
        },
        "s_per_round": {
            "memory": mem_row["round_wall_s"],
            "ici": ici_row["round_wall_s"],
        },
        "backend": "ppermute-fallback (CPU virtual devices)",
    }
    # DCN plane vs byte path across a REAL process boundary (two OS
    # processes, one jax.distributed world) — grpc_weight_bytes drops to
    # zero while the payloads move device-to-device via collectives
    results["dcn"] = bench_dcn(rounds=2)
    # streaming byte plane: ≥100 MB model over real loopback gRPC, chunked
    # stream vs the option-raised unary path — wall-clock, peak RSS and the
    # measured receiver scratch bound are the row's claims
    results["stream"] = bench_stream(size_mb=104, chunk_mb=4.0)
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(json.dumps(results, indent=2))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
